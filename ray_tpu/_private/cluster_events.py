"""Cluster event plane + failure flight recorder.

The runtime's *metrics* plane (``runtime_metrics.py``) answers "how fast
is it"; this module answers "what happened" — and, after a death, "why".
Three pieces (docs/observability.md):

* **EventRecorder** — a bounded, lock-cheap per-process recorder of
  typed lifecycle events (node up/down, worker spawn/exit, actor
  restart, lease timeout, object spill/restore, transfer source
  failover, collective rank death, serve replica retire/autoscale).
  ``emit()`` is one dict build + two deque appends; a background
  flusher (same cadence philosophy as the ``runtime_metrics`` flusher:
  periodic, dirty-only, never on the hot path) batches events to the
  GCS and — this is the flight-recorder half — atomically dumps the
  in-memory ring to a per-process *flight file* on disk, so the last N
  events survive the process that recorded them.  ``ring_only=True``
  events (per-task breadcrumbs) stay out of the GCS batch but land in
  the ring/flight file, so a crash dossier shows what the worker was
  doing without the cluster table drowning in per-task noise.

* **GcsClusterEventTable** — the GCS-side aggregation point (the Ray
  paper's GCS-centric design makes the control store the natural home
  for cluster-wide lifecycle state): sharded deques, retention bounded
  by BOTH a max event count (``gcs_max_cluster_events``) and a max
  byte budget (``gcs_events_max_bytes``), queryable with filters
  (node/job/actor/worker/severity/type) via
  ``experimental.state.list_cluster_events()`` / ``ray-tpu events``.

* **Dossiers** — on an abnormal worker exit the raylet harvests the
  worker's flight file, the tail of its logs and its last metrics
  watermarks into a crash dossier stored in the GCS (bounded,
  ``gcs_max_dossiers``); ``RayTaskError``-family exceptions carry a
  ``dossier_id`` and ``.debug_dossier()`` fetches + pretty-prints it
  at the driver.

Kill switch: ``RAY_TPU_EVENTS=0`` (or ``CONFIG.events_enabled=False``)
swaps the recorder for a no-op — ``emit()`` returns after one global
read, the flusher never starts, and nothing is written anywhere
(mirrors ``RAY_TPU_TELEMETRY``; benchmarks/telemetry_overhead.py
--events holds the on-cost to the same <= 3% bar).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.config import CONFIG

# ------------------------------------------------------------ event types
# node lifecycle (emitted by the GCS)
NODE_UP = "NODE_UP"
NODE_DEAD = "NODE_DEAD"
NODE_UNHEALTHY = "NODE_UNHEALTHY"
NODE_HEALTHY = "NODE_HEALTHY"
# preemption lifecycle (docs/fault_tolerance.md): a drain request marks
# the node PREEMPTING with a grace deadline; the raylet stops granting
# leases, lets short tasks finish, evacuates primary copies, then
# reports DRAINED with the evacuation ledger
NODE_PREEMPTING = "NODE_PREEMPTING"
NODE_DRAINED = "NODE_DRAINED"
OBJECT_EVACUATED = "OBJECT_EVACUATED"
# worker lifecycle (emitted by the raylet)
WORKER_SPAWN = "WORKER_SPAWN"
WORKER_EXIT = "WORKER_EXIT"
OOM_KILL = "OOM_KILL"
# actor FSM (emitted by the GCS)
ACTOR_RESTARTING = "ACTOR_RESTARTING"
ACTOR_DEAD = "ACTOR_DEAD"
# scheduling
LEASE_TIMEOUT = "LEASE_TIMEOUT"
# object store
OBJECT_SPILL = "OBJECT_SPILL"
OBJECT_RESTORE = "OBJECT_RESTORE"
SPILL_WRITE_FAILED = "SPILL_WRITE_FAILED"
OUT_OF_DISK = "OUT_OF_DISK"
# data planes
TRANSFER_FAILOVER = "TRANSFER_FAILOVER"
COLLECTIVE_RANK_DEATH = "COLLECTIVE_RANK_DEATH"
COLLECTIVE_RING_STALL = "COLLECTIVE_RING_STALL"
# serving
REPLICA_RETIRED = "REPLICA_RETIRED"
AUTOSCALE = "AUTOSCALE"
# SLO-driven pool re-roling (docs/serve_frontdoor.md): the controller
# moves a replica between a <base>-prefill/<base>-decode pair — REROLE
# opens when the donor replica starts draining, REROLE_DONE closes when
# the receiver pool is healthy at its new target and the donor retired
SERVE_REROLE = "SERVE_REROLE"
SERVE_REROLE_DONE = "SERVE_REROLE_DONE"
# training performance plane (emitted by the GCS step-stats table,
# docs/observability.md): a gang rank's step time crossed
# median + k*MAD — the degraded rank names itself (rank/step/phase)
# instead of silently dragging the allreduce
TRAIN_STRAGGLER = "TRAIN_STRAGGLER"
# elastic gang recovery (docs/fault_tolerance.md): the trainer driver
# detected rank/node death (event plane or poll failure), re-formed the
# gang and resumed from the latest reported checkpoint
TRAIN_GANG_RECOVERY = "TRAIN_GANG_RECOVERY"
# RL podracer fleet (docs/rl_podracer.md): a rollout actor's stream
# died (preemption/crash) — the learner keeps stepping on the survivors
# — and the replacement actor finished rendezvous (weights pulled,
# stream re-established).  The recovery auditor pairs them per fleet
# slot into `rl_actor` episodes.
RL_ACTOR_LOST = "RL_ACTOR_LOST"
RL_ACTOR_JOINED = "RL_ACTOR_JOINED"
# flight-recorder breadcrumbs (ring_only by convention)
TASK_RUNNING = "TASK_RUNNING"
TASK_FAILED = "TASK_FAILED"

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


def enabled() -> bool:
    """Kill switch: RAY_TPU_EVENTS env wins, then the config flag."""
    raw = os.environ.get("RAY_TPU_EVENTS")
    if raw is not None:
        return raw.strip().lower() not in ("0", "false", "no", "off")
    return CONFIG.events_enabled


class EventRecorder:
    """Per-process event ring + GCS flusher + flight file.

    ``emit()`` never blocks on IO: it appends to the bounded ring (and,
    unless ``ring_only``, to the unflushed batch) under one short lock;
    the flusher thread ships batches and rewrites the flight file."""

    def __init__(self, *, sink: Optional[Callable[[List[dict]], Any]] = None,
                 source: str = "proc", node_id: str = "",
                 worker_id: str = "", job_id: str = "",
                 flight_path: Optional[str] = None):
        self._sink = sink
        self.source = source
        self._defaults = {"node_id": node_id, "worker_id": worker_id,
                          "job_id": job_id, "pid": os.getpid()}
        self._ring: deque = deque(maxlen=max(16, CONFIG.event_ring_size))
        self._unflushed: List[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flight_path = flight_path
        self._ring_dirty = False

    def emit(self, etype: str, message: str = "", *,
             severity: str = "INFO", ring_only: bool = False,
             **fields: Any) -> None:
        ev = {"ts": time.time(), "type": etype, "severity": severity,
              "source": self.source, "message": message}
        ev.update(self._defaults)
        for k, v in fields.items():
            if v is not None:
                ev[k] = v
        with self._lock:
            self._ring.append(ev)
            self._ring_dirty = True
            if not ring_only and self._sink is not None \
                    and not self._stop.is_set():
                self._unflushed.append(ev)
            start = (self._thread is None
                     and (self._sink is not None or self._flight_path)
                     and not self._stop.is_set())
            if start:
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name="cluster-events-flush")
                self._thread.start()

    def ring_snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def flush(self) -> None:
        """One flusher tick: ship the unflushed batch (re-queued on a
        sink failure — the GCS going away must never kill the process)
        and rewrite the flight file if the ring changed."""
        with self._lock:
            batch, self._unflushed = self._unflushed, []
            dirty, self._ring_dirty = self._ring_dirty, False
            ring = list(self._ring) if (dirty and self._flight_path) \
                else None
        if batch and self._sink is not None:
            try:
                self._sink(batch)
            except Exception:
                # sink down (GCS outage): re-queue, but bound the
                # COMBINED backlog — keep only the newest ring's worth,
                # or a long outage grows memory (and the retry payload)
                # by the emission rate for its whole duration
                with self._lock:
                    self._unflushed = (batch + self._unflushed)[
                        -max(16, CONFIG.event_ring_size):]
        if ring is not None:
            self._write_flight(ring)

    def _write_flight(self, ring: List[dict]) -> None:
        """Atomic (tmp+rename) dump of the ring so a SIGKILL mid-write
        can't leave the raylet harvesting a torn file."""
        try:
            tmp = f"{self._flight_path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(ring, f, default=str)
            os.replace(tmp, self._flight_path)
        except OSError:
            pass

    def _flush_loop(self) -> None:
        period = max(0.05, CONFIG.events_flush_interval_ms / 1000.0)
        while not self._stop.wait(period):
            self.flush()
        self.flush()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self.flush()


# -------------------------------------------------- module-level recorder
_recorder: Optional[EventRecorder] = None
_rec_lock = threading.Lock()


def configure(*, sink: Optional[Callable[[List[dict]], Any]],
              source: str, node_id: str = "", worker_id: str = "",
              job_id: str = "",
              flight_path: Optional[str] = None) -> Optional[EventRecorder]:
    """Bind this process's recorder (one per process; a fresh init()
    replaces it).  No-op returning None when the plane is disabled —
    every ``emit()`` then costs one global read."""
    global _recorder
    with _rec_lock:
        old, _recorder = _recorder, None
    if old is not None:
        old.stop()
    # raylint: disable=kill-switch -- configure() runs once per init(), not per emit; emit()'s own guard is one global read
    if not enabled():
        return None
    rec = EventRecorder(sink=sink, source=source, node_id=node_id,
                        worker_id=worker_id, job_id=job_id,
                        flight_path=flight_path)
    with _rec_lock:
        _recorder = rec
    return rec


def detach(rec: Optional[EventRecorder] = None) -> None:
    """Unbind at owner shutdown; with ``rec`` given, only if it is
    still the active recorder (a newer owner's configure survives)."""
    global _recorder
    with _rec_lock:
        if rec is None or _recorder is rec:
            old, _recorder = _recorder, None
        else:
            old = None
    if old is not None:
        old.stop()


def emit(etype: str, message: str = "", *, severity: str = "INFO",
         ring_only: bool = False, **fields: Any) -> None:
    """Record one typed event on this process's recorder (dropped when
    the plane is disabled or the process never configured one)."""
    rec = _recorder
    if rec is not None:
        rec.emit(etype, message, severity=severity, ring_only=ring_only,
                 **fields)


def ring_snapshot() -> List[dict]:
    rec = _recorder
    return rec.ring_snapshot() if rec is not None else []


def flight_file_name(worker_id_hex: str) -> str:
    """Flight-file basename for a worker id — the raylet derives the
    same name to harvest the ring post-mortem."""
    return f"flight-{worker_id_hex[:12]}.json"


def read_flight_file(session_dir: str, worker_id_hex: str) -> List[dict]:
    """Best-effort read of a (possibly dead) worker's flight ring."""
    path = os.path.join(session_dir, "logs",
                        flight_file_name(worker_id_hex))
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, list) else []
    except (OSError, ValueError):
        return []


# ------------------------------------------------------- GCS event table
class GcsClusterEventTable:
    """Sharded, retention-bounded cluster event store.

    Shards keep appends lock-cheap under many concurrent flusher
    batches; retention is bounded twice — per-shard event count derived
    from ``gcs_max_cluster_events`` and a table-wide byte budget
    (``gcs_events_max_bytes``, measured as the JSON size of each
    record) so a chatty cluster can never grow GCS memory without
    bound.  Queries merge shards and sort by timestamp."""

    NSHARDS = 8

    def __init__(self, max_events: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.max_events = max_events or CONFIG.gcs_max_cluster_events
        self.max_bytes = max_bytes or CONFIG.gcs_events_max_bytes
        per = max(4, self.max_events // self.NSHARDS)
        self._shards = [deque() for _ in range(self.NSHARDS)]
        self._locks = [threading.Lock() for _ in range(self.NSHARDS)]
        self._per_shard = per
        self._bytes = 0
        self._bytes_lock = threading.Lock()
        self._counts: Dict[str, int] = {}   # type -> total ever seen
        self._rr = 0

    def _shard_of(self, ev: dict) -> int:
        # round-robin, NOT keyed on node/worker id: a single-node
        # cluster (the common dev topology) would otherwise pile every
        # event into one shard and silently cap retention at
        # max_events/NSHARDS.  Queries merge+sort across shards, so
        # placement carries no meaning — the shards exist only to keep
        # concurrent flusher batches off one lock.
        self._rr = (self._rr + 1) % self.NSHARDS
        return self._rr

    @staticmethod
    def _size_of(ev: dict) -> int:
        try:
            return len(json.dumps(ev, default=str))
        except (TypeError, ValueError):
            return 256

    def put(self, events: List[dict]) -> int:
        """Merge one batch; returns how many old events rotation
        dropped.  Events missing a timestamp are stamped on arrival."""
        dropped = 0
        for ev in events:
            if not isinstance(ev, dict) or not ev.get("type"):
                continue
            ev.setdefault("ts", time.time())
            ev.setdefault("severity", "INFO")
            size = self._size_of(ev)
            i = self._shard_of(ev)
            with self._locks[i]:
                shard = self._shards[i]
                shard.append((ev, size))
                evicted = []
                while len(shard) > self._per_shard:
                    evicted.append(shard.popleft())
                    dropped += 1
            delta = size - sum(s for _e, s in evicted)
            with self._bytes_lock:
                # _counts shares this lock: concurrent batches land on
                # DIFFERENT shards, so a per-shard lock can't order two
                # read-modify-writes of the same type's counter
                self._bytes += delta
                self._counts[ev["type"]] = \
                    self._counts.get(ev["type"], 0) + 1
        # byte budget: evict oldest-first round-robin across shards
        # (outside the per-event loop so one oversized batch settles in
        # one sweep)
        while True:
            with self._bytes_lock:
                if self._bytes <= self.max_bytes:
                    break
            victim = None
            oldest = None
            for i in range(self.NSHARDS):
                with self._locks[i]:
                    if self._shards[i]:
                        ts = self._shards[i][0][0].get("ts", 0)
                        if oldest is None or ts < oldest:
                            oldest, victim = ts, i
            if victim is None:
                break
            with self._locks[victim]:
                if not self._shards[victim]:
                    continue
                _ev, size = self._shards[victim].popleft()
            dropped += 1
            with self._bytes_lock:
                self._bytes -= size
        return dropped

    def list(self, *, node_id: Optional[str] = None,
             job_id: Optional[str] = None,
             actor_id: Optional[str] = None,
             worker_id: Optional[str] = None,
             severity: Optional[str] = None,
             min_severity: Optional[str] = None,
             etype: Optional[str] = None,
             source: Optional[str] = None,
             limit: int = 1000) -> List[dict]:
        out: List[dict] = []
        for i in range(self.NSHARDS):
            with self._locks[i]:
                rows = [ev for ev, _s in self._shards[i]]
            for ev in rows:
                if node_id and not str(ev.get("node_id", "")).startswith(
                        node_id):
                    continue
                if worker_id and not str(ev.get("worker_id", "")
                                         ).startswith(worker_id):
                    continue
                if job_id and not str(ev.get("job_id", "")).startswith(
                        job_id):
                    continue
                if actor_id and not str(ev.get("actor_id", "")
                                        ).startswith(actor_id):
                    continue
                if severity and ev.get("severity") != severity:
                    continue
                if min_severity and _SEV_RANK.get(
                        ev.get("severity", "INFO"), 1) < _SEV_RANK.get(
                        min_severity, 0):
                    continue
                if etype and ev.get("type") != etype:
                    continue
                if source and ev.get("source") != source:
                    continue
                out.append(ev)
        out.sort(key=lambda e: e.get("ts", 0))
        # copy only the returned tail: records are immutable after
        # put(), and an unfiltered query over 20k retained events must
        # not build 20k dict copies to return 200
        return [dict(ev) for ev in out[-max(0, int(limit)):]]

    def counts_by_type(self) -> Dict[str, int]:
        """Total events ever recorded per type (survives rotation —
        the metrics_summary 'top event types' view)."""
        with self._bytes_lock:
            return dict(self._counts)

    def stats(self) -> dict:
        with self._bytes_lock:
            nbytes = self._bytes
        return {"events": sum(len(s) for s in self._shards),
                "bytes": nbytes, "max_events": self.max_events,
                "max_bytes": self.max_bytes}


# ------------------------------------------------------------- dossiers
def fetch_dossier(dossier_id: str, timeout: float = 10.0
                  ) -> Optional[dict]:
    """Driver-side dossier fetch by id (worker id hex for worker
    deaths, node id hex for node deaths) via the connected cluster."""
    from ray_tpu.runtime import core_worker as cw
    worker = cw.get_global_worker()
    if worker is None:
        return None
    return worker.gcs.call("get_dossier", {"dossier_id": dossier_id},
                           timeout=timeout)


def format_dossier(d: dict) -> str:
    """Human-readable crash dossier (``.debug_dossier()`` and
    ``ray-tpu events --dossier``)."""
    if not d:
        return "(no dossier)"
    lines = []
    kind = d.get("kind", "worker")
    ident = d.get("worker_id") or d.get("node_id") or "?"
    lines.append(f"=== crash dossier: {kind} {str(ident)[:16]} ===")
    for key in ("reason", "exit_code", "oom", "node_id", "worker_id",
                "actor_id", "job_id", "pid", "ts"):
        if d.get(key) not in (None, ""):
            val = d[key]
            if key == "ts":
                val = time.strftime("%Y-%m-%d %H:%M:%S",
                                    time.localtime(val))
            lines.append(f"{key:>10}: {val}")
    health = d.get("health")
    if health:
        lines.append(f"{'health':>10}: " + ", ".join(
            f"{k}={v}" for k, v in sorted(health.items())))
    events = d.get("events") or []
    if events:
        lines.append(f"-- last {len(events)} events --")
        for ev in events[-40:]:
            ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
            extra = ev.get("message") or ev.get("name") or ""
            lines.append(f"  {ts} [{ev.get('severity', '?'):7s}] "
                         f"{ev.get('type', ev.get('state', '?')):22s} "
                         f"{extra}")
    metrics = d.get("metrics") or {}
    if metrics:
        lines.append("-- last metrics watermarks --")
        for name, values in sorted(metrics.items()):
            lines.append(f"  {name}: {values}")
    stacks = d.get("stacks")
    if stacks:
        lines.append("-- stacks at kill (folded, top) --")
        from ray_tpu._private.profiler import top_summary
        if isinstance(stacks, dict):
            lines.append(top_summary(stacks))
        else:
            lines.append(str(stacks))
    for stream in ("err", "out"):
        tail = (d.get("log_tail") or {}).get(stream)
        if tail:
            lines.append(f"-- log tail ({stream}) --")
            lines.append(tail.rstrip())
    return "\n".join(lines)
