"""Version-compat shims for renamed jax APIs.

The kernels/meshes in this repo are written against the current jax
names; older installs (and some TPU-pinned builds) still carry the
pre-rename ones.  Everything here resolves at import time against the
installed jax so call sites stay on one spelling:

  - ``shard_map``: ``jax.shard_map`` vs
    ``jax.experimental.shard_map.shard_map`` (which also still calls
    the ``check_vma`` kwarg ``check_rep``).
  - ``axis_size``: ``jax.lax.axis_size`` vs the classic
    ``lax.psum(1, axis)`` — which constant-folds to a python int inside
    shard_map, so it stays usable as a static bound (``range(n)``).
  - ``tpu_compiler_params``: ``pltpu.CompilerParams`` vs
    ``pltpu.TPUCompilerParams``.
  - ``Mesh`` / ``PartitionSpec`` / ``NamedSharding``: ``jax.sharding``
    vs the pre-0.4 spellings (``jax.interpreters.pxla.Mesh``,
    ``jax.experimental.PartitionSpec``).  The sharded-training planner
    (ray_tpu/train/sharded/) imports these from here so the whole
    subsystem tracks one resolution instead of per-file try/excepts.
"""

from __future__ import annotations

import jax

try:
    from jax.sharding import Mesh, NamedSharding, PartitionSpec
except ImportError:                                   # pre-jax.sharding era
    from jax.experimental import PartitionSpec        # type: ignore
    from jax.experimental.maps import Mesh            # type: ignore
    try:
        from jax.experimental.pjit import \
            NamedSharding                             # type: ignore
    except ImportError:
        NamedSharding = None                          # type: ignore

try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_legacy(*args, **kwargs)


def axis_size(axis_name):
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def tpu_compiler_params(**kwargs):
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    return cls(**kwargs)
