"""Reusable test doubles and helpers.

Analog of /root/reference/python/ray/_private/test_utils.py — the
reference's most-reused testing pattern (SURVEY.md §4): actor-based
synchronization primitives tasks can rendezvous on (SignalActor :704,
Semaphore :725), condition polling (wait_for_condition :461), and
driver-script isolation (run_string_as_driver :329).  The chaos
NodeKiller analog lives in ``_private/chaos.py``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Callable, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class SignalActor:
    """A distributed Event: tasks block on ``wait`` until ``send``.

    >>> sig = SignalActor.remote()
    >>> ray_tpu.get(sig.wait.remote())   # blocks until somebody sends
    """

    def __init__(self):
        import asyncio
        self._event = asyncio.Event()
        self._num_waiters = 0

    async def send(self, clear: bool = False):
        self._event.set()
        if clear:
            self._event.clear()

    async def wait(self, should_wait: bool = True):
        if should_wait:
            self._num_waiters += 1
            try:
                await self._event.wait()
            finally:
                self._num_waiters -= 1

    async def cur_num_waiters(self) -> int:
        return self._num_waiters


@ray_tpu.remote(num_cpus=0)
class Semaphore:
    """A distributed semaphore for throttling/rendezvous in tests."""

    def __init__(self, value: int = 1):
        import asyncio
        self._sema = asyncio.Semaphore(value=value)

    async def acquire(self):
        await self._sema.acquire()

    async def release(self):
        self._sema.release()

    async def locked(self) -> bool:
        return self._sema.locked()


def wait_for_condition(condition_predictor: Callable[..., bool],
                       timeout: float = 10.0,
                       retry_interval_ms: int = 100,
                       raise_exceptions: bool = False,
                       **kwargs: Any) -> None:
    """Poll until the predicate is True or raise RuntimeError with the
    last exception seen (reference wait_for_condition semantics)."""
    start = time.monotonic()
    last_ex: Optional[BaseException] = None
    while time.monotonic() - start <= timeout:
        try:
            if condition_predictor(**kwargs):
                return
        except Exception as e:  # noqa: BLE001 - surfaced on timeout
            if raise_exceptions:
                raise
            last_ex = e
        time.sleep(retry_interval_ms / 1000.0)
    message = "The condition wasn't met before the timeout expired."
    if last_ex is not None:
        message += f" Last exception: {last_ex!r}"
    raise RuntimeError(message)


def run_string_as_driver(script: str,
                         env: Optional[dict] = None,
                         timeout: float = 120.0) -> str:
    """Run a script as a separate driver process; returns its stdout,
    raising on non-zero exit with stderr in the message."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    if env:
        child_env.update(env)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True,
                          env=child_env, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"driver script failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-4000:]}")
    return proc.stdout


def run_string_as_driver_nonblocking(script: str,
                                     env: Optional[dict] = None
                                     ) -> subprocess.Popen:
    """Start a driver script without waiting (reference :362)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    child_env = dict(os.environ)
    child_env["PYTHONPATH"] = repo + os.pathsep + \
        child_env.get("PYTHONPATH", "")
    if env:
        child_env.update(env)
    return subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, env=child_env)
