"""Local filesystem capacity monitor.

Analog of /root/reference/src/ray/common/file_system_monitor.h
(``FileSystemMonitor``: polls disk usage of the spill/fallback paths and
makes writes fail gracefully above ``local_fs_capacity_threshold``
instead of filling the disk and hanging the node).

Test seam: ``fs_monitor_test_usage_path`` names a file holding a float
usage fraction read instead of the kernel counters — the same shape as
the memory monitor's injection seam.
"""

from __future__ import annotations

import logging
import shutil
import time

from ray_tpu._private.config import CONFIG

logger = logging.getLogger(__name__)


class FileSystemMonitor:
    def __init__(self, path: str,
                 capacity_threshold: float = None,
                 check_interval_s: float = 1.0,
                 on_over=None):
        self.path = path
        self.capacity_threshold = (
            CONFIG.local_fs_capacity_threshold
            if capacity_threshold is None else capacity_threshold)
        self.check_interval_s = check_interval_s
        self.on_over = on_over   # fired once on each not-full->full edge
        self._last_check = 0.0
        self._last_usage = 0.0
        self._warned = False

    def usage_fraction(self) -> float:
        now = time.monotonic()
        if now - self._last_check < self.check_interval_s:
            return self._last_usage
        self._last_check = now
        test_path = CONFIG.fs_monitor_test_usage_path
        if test_path:
            try:
                with open(test_path) as f:
                    self._last_usage = float(f.read().strip())
                return self._last_usage
            except (OSError, ValueError):
                pass
        try:
            du = shutil.disk_usage(self.path)
            self._last_usage = du.used / max(1, du.total)
        except OSError:
            self._last_usage = 0.0
        return self._last_usage

    def over_capacity(self) -> bool:
        usage = self.usage_fraction()
        over = usage >= self.capacity_threshold
        if over and not self._warned:
            self._warned = True
            logger.error(
                "local filesystem holding %s is %.1f%% full "
                "(threshold %.0f%%): object spilling and fallback "
                "allocation are disabled until space frees up",
                self.path, usage * 100, self.capacity_threshold * 100)
            if self.on_over is not None:
                try:
                    self.on_over(usage)
                except Exception:
                    pass
        elif not over:
            self._warned = False
        return over
