"""The sixth observability plane: metrics history + recovery auditing.

The five existing planes answer "what is happening NOW" (metrics
snapshots), "what happened" (typed events + dossiers), "how fast is
training" (step stats), "where did a request go" (traces) and "what
did tasks do" (task events).  None of them answers the two questions a
preemption post-mortem actually starts with: *what did the pool look
like during the outage* and *how long did recovery take* — the first
needs metric values over a window, the second is hand-computed from
event timestamps inside individual tests today.  This module is that
layer, GCS-side like every other table:

* **GcsMetricsHistoryTable** — the metrics sink's KV writes
  (``metrics/{name}/{ident}``) additionally land in a bounded,
  multi-resolution downsampled ring per series (default 1s x 120 /
  10s x 180 / 60s x 120: two minutes fine, half an hour medium, two
  hours coarse).  Within a bucket last-write-wins — the flusher
  snapshots are already cumulative, so keeping the newest payload per
  bucket IS downsampling for counters/histograms and sample-and-hold
  for gauges.  Payloads are stored as raw bytes and parsed only at
  query time: the record path is a ring append, not a JSON parse.
  Count- AND byte-budgeted like the event/span tables.

* **RecoveryAuditor** — folds the typed event stream into first-class
  recovery *episodes*: NODE_PREEMPTING -> NODE_DRAINED (drain latency
  + evacuation ledger), NODE_PREEMPTING/NODE_DEAD ->
  TRAIN_GANG_RECOVERY (time-to-failover + re-executed-step lost
  work), REPLICA_RETIRED -> AUTOSCALE (pool-heal latency), plus
  TRANSFER_FAILOVER counts.  Episodes are classified against the
  ``recovery_slo_*`` targets, published as ``ray_tpu_recovery_*``
  metric families (which the history table then retains — the auditor
  feeds the plane it rides on) and kept in a bounded per-episode table
  whose per-kind counters survive rotation, exactly like the event
  table's ``counts_by_type``.

* **doctor** — ``build_doctor_report`` correlates one snapshot of all
  six planes (node health, recent events, episodes + SLO violations,
  straggler flags, worst-trace exemplars, open dossiers, history
  stats) into ranked findings with evidence lines; the pure-function
  split keeps it unit-testable without a cluster and reusable by the
  CLI (``ray-tpu doctor``), the dashboard (``/api/doctor``) and the
  one-shot ``ray-tpu debug-bundle`` tarball.

Kill switch: ``RAY_TPU_METRICS_HISTORY`` env wins, then
``CONFIG.metrics_history_enabled``; hot-path call sites read the
``history_on()`` generation-keyed cache (the tracing ``_flags``
idiom).  Everything here is ephemeral — never WALed, like metrics,
events and traces.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private.config import CONFIG


def enabled() -> bool:
    """Kill switch: RAY_TPU_METRICS_HISTORY env wins, then the flag."""
    raw = os.environ.get("RAY_TPU_METRICS_HISTORY")
    if raw is not None:
        return raw.strip().lower() not in ("0", "false", "no", "off")
    return CONFIG.metrics_history_enabled


# enabled() is consulted on every metrics KV write and every event-table
# put reaching the GCS: cache the verdict keyed on the CONFIG override
# generation so the steady state pays a tuple compare, not an env read
# plus a config lock
_flag_cache = (-1, False)


def history_on() -> bool:
    global _flag_cache
    gen = CONFIG.generation()
    cached = _flag_cache
    if cached[0] != gen:
        cached = (gen, enabled())
        _flag_cache = cached
    return cached[1]


def parse_resolutions(spec: str) -> List[Tuple[float, int]]:
    """``"1:120,10:180,60:120"`` -> ``[(1.0, 120), (10.0, 180), ...]``
    (seconds-per-bucket : slots).  Malformed entries are skipped; an
    empty/unusable spec falls back to the declared default so a typo'd
    override degrades to the stock retention, not to no retention."""
    out: List[Tuple[float, int]] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            res, slots = part.split(":")
            res_s, nslots = float(res), int(slots)
        except ValueError:
            continue
        if res_s > 0 and nslots > 0:
            out.append((res_s, nslots))
    if not out:
        out = [(1.0, 120), (10.0, 180), (60.0, 120)]
    return sorted(out)


# ------------------------------------------------------- history table
class GcsMetricsHistoryTable:
    """Bounded multi-resolution retention over the metrics KV stream.

    One series per KV key (``metrics/{name}/{ident}``); per series one
    ring per configured resolution, each slot holding the newest raw
    payload whose arrival time fell in that bucket.  The record path is
    O(1) in the common case: each write just replaces the series'
    single **pending** value (last-write-wins for every live bucket at
    once), and the rings are only touched when a write crosses
    ``next_roll`` — the earliest upcoming bucket boundary across the
    rings — at which point the pending value is sealed into every ring
    whose bucket closed.  The live bucket is synthesized from the
    pending value at query time, so readers still see the newest
    sample; the flusher-driven ingest path never pays per-ring work.

    The GCS KV path goes through :meth:`ingest`, which defers even
    that: the write is stamped with its arrival time, appended to a
    lock-free staging deque, and folded in batches of
    ``_INGEST_BATCH`` under a single lock acquisition — so the RPC
    reply never waits on table work, the way span shipping rides the
    tracing flusher thread instead of the submit path.  Readers drain
    the staging queue first (read-your-writes), and bucket assignment
    uses the stamped arrival time, so batching changes *when* the fold
    runs, never *what* it produces.

    Byte accounting charges every stored slot (the same payload may
    occupy one slot in each ring) plus one pending value per series;
    the budget sweep drops the globally oldest stored point first, and
    the series cap evicts the longest-idle series — both mirror the
    event table's oldest-first discipline."""

    # staged writes folded per lock acquisition: big enough to amortize
    # the lock + attribute traffic, small enough that one fold burst
    # (~60us) stays invisible next to an RPC round trip
    _INGEST_BATCH = 64

    def __init__(self, resolutions: Optional[List[Tuple[float, int]]] = None,
                 max_series: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        # binding time: the retention geometry is fixed at construction
        # (one table per GCS lifetime), like the event table's shard cap
        self._resolutions = (resolutions if resolutions is not None
                             else parse_resolutions(
                                 CONFIG.metrics_history_resolutions))
        self._max_series = (CONFIG.gcs_metrics_history_max_series
                            if max_series is None else max_series)
        self._max_bytes = (CONFIG.gcs_metrics_history_max_bytes
                           if max_bytes is None else max_bytes)
        self._lock = threading.Lock()
        # key -> {"name", "ident", "last_ts", "last_raw", "next_roll",
        #         "live": [bucket per ring],
        #         "rings": [deque[(bucket, ts, raw)]]}
        self._series: Dict[str, Dict[str, Any]] = {}
        self._bytes = 0
        self._dropped_points = 0
        self._evicted_series = 0
        # staging queue of (key, raw, arrival_ts) awaiting a batch fold;
        # deque append/popleft are atomic, so ingest() takes no lock
        self._staged: deque = deque()

    @staticmethod
    def _split_key(key: str) -> Tuple[str, str]:
        parts = key.split("/", 2)
        if len(parts) == 3 and parts[0] == "metrics":
            return parts[1], parts[2]
        return key, ""

    def ingest(self, key: str, raw: bytes) -> None:
        """Stage one KV write for folding: stamp its arrival time and
        return immediately; every ``_INGEST_BATCH``-th write folds the
        accumulated batch.  This is the hot-path entry the GCS KV
        handlers call — the RPC reply never waits on ring work."""
        self._staged.append((key, raw, time.time()))
        if len(self._staged) >= self._INGEST_BATCH:
            self.drain()

    def drain(self) -> None:
        """Fold every staged write (arrival-time-stamped, FIFO) into
        the rings.  Called by the batch threshold and by every reader,
        so queries always see writes that preceded them."""
        while True:
            try:
                key, raw, ts = self._staged.popleft()
            except IndexError:
                return
            self.record(key, raw, now=ts)

    def record(self, key: str, raw: bytes, now: Optional[float] = None) \
            -> None:
        """One flusher snapshot for ``key`` landed.  Fast path (no
        bucket boundary crossed since the last write): replace the
        series' pending value — one comparison, no ring work."""
        if not isinstance(raw, (bytes, bytearray)):
            raw = str(raw).encode()
        now = time.time() if now is None else now
        size = len(raw)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                name, ident = self._split_key(key)
                live = [int(now // res) for res, _ in self._resolutions]
                s = {"name": name, "ident": ident, "last_ts": now,
                     "last_raw": raw,
                     "next_roll": min((b + 1) * res for b, (res, _) in
                                      zip(live, self._resolutions)),
                     "live": live,
                     "rings": [deque() for _ in self._resolutions]}
                self._series[key] = s
                self._bytes += size
                while len(self._series) > self._max_series:
                    self._evict_idlest_series_locked()
            else:
                if now >= s["next_roll"]:
                    self._roll_locked(s, now)
                self._bytes += size - len(s["last_raw"])
                s["last_raw"] = raw
                s["last_ts"] = now
            if self._bytes > self._max_bytes:
                while self._bytes > self._max_bytes and self._series:
                    self._drop_oldest_point_locked()

    def _roll_locked(self, s: Dict[str, Any], now: float) -> None:
        """A write crossed a bucket boundary: seal the pending value
        into every ring whose live bucket closed (it was the last write
        of that bucket — last-write-wins), advance the live buckets,
        and recompute the earliest next boundary."""
        raw, ts = s["last_raw"], s["last_ts"]
        size = len(raw)
        live = s["live"]
        next_roll = None
        for i, (res, slots) in enumerate(self._resolutions):
            b = int(now // res)
            if b != live[i]:
                ring = s["rings"][i]
                ring.append((live[i], ts, raw))
                self._bytes += size
                while len(ring) > slots:
                    old = ring.popleft()
                    self._bytes -= len(old[2])
                    self._dropped_points += 1
                live[i] = b
            nr = (live[i] + 1) * res
            if next_roll is None or nr < next_roll:
                next_roll = nr
        s["next_roll"] = next_roll

    def _evict_idlest_series_locked(self) -> None:
        key = min(self._series, key=lambda k: self._series[k]["last_ts"])
        s = self._series.pop(key)
        self._bytes -= len(s["last_raw"])
        self._dropped_points += 1   # the pending value goes with it
        for ring in s["rings"]:
            for _, _, raw in ring:
                self._bytes -= len(raw)
                self._dropped_points += 1
        self._evicted_series += 1

    def _drop_oldest_point_locked(self) -> None:
        """Byte-budget sweep step: drop the globally oldest stored
        point (across all series and rings), oldest-ts-first like the
        event table's budget eviction.  When only pending values remain
        there is no stored point to drop, so the sweep falls back to
        evicting the longest-idle series whole."""
        best_ring, best_ts = None, None
        for s in self._series.values():
            for ring in s["rings"]:
                if ring and (best_ts is None or ring[0][1] < best_ts):
                    best_ring, best_ts = ring, ring[0][1]
        if best_ring is None:
            self._evict_idlest_series_locked()
            return
        old = best_ring.popleft()
        self._bytes -= len(old[2])
        self._dropped_points += 1

    def series(self) -> List[Dict[str, Any]]:
        self.drain()   # read-your-writes over the staging queue
        with self._lock:
            # per-ring counts are sealed points; every series also
            # carries one pending (live-bucket) value on top
            return [{"key": k, "name": s["name"], "ident": s["ident"],
                     "last_ts": s["last_ts"],
                     "points": [len(r) for r in s["rings"]]}
                    for k, s in sorted(self._series.items())]

    def query(self, name: Optional[str] = None,
              ident: Optional[str] = None,
              since: Optional[float] = None,
              resolution: Optional[float] = None,
              limit: int = 2000) -> List[Dict[str, Any]]:
        """Parsed points, oldest first.  ``resolution`` picks the ring
        whose bucket width is closest to the request (finest by
        default); payload JSON is decoded here, at query time, never on
        the record path."""
        self.drain()   # read-your-writes over the staging queue
        with self._lock:
            items = [(k, s) for k, s in self._series.items()
                     if (name is None or s["name"] == name)
                     and (ident is None or s["ident"] == ident)]
            raw_pts: List[Tuple[float, float, str, str, bytes]] = []
            for _k, s in items:
                idx = 0
                if resolution is not None:
                    idx = min(range(len(self._resolutions)),
                              key=lambda i: abs(
                                  self._resolutions[i][0] - resolution))
                res = self._resolutions[idx][0]
                for _bucket, ts, raw in s["rings"][idx]:
                    if since is not None and ts < since:
                        continue
                    raw_pts.append((ts, res, s["name"], s["ident"], raw))
                # the live bucket's value lives as the series' pending
                # slot until the bucket closes — surface it here so
                # readers always see the newest sample
                if since is None or s["last_ts"] >= since:
                    raw_pts.append((s["last_ts"], res, s["name"],
                                    s["ident"], s["last_raw"]))
        raw_pts.sort(key=lambda p: p[0])
        out: List[Dict[str, Any]] = []
        for ts, res, sname, sident, raw in raw_pts[-max(0, limit):]:
            try:
                blob = json.loads(raw)
            except (ValueError, TypeError):
                continue
            out.append({"ts": ts, "res_s": res, "name": sname,
                        "ident": sident,
                        "type": blob.get("type", "gauge"),
                        "values": blob.get("values", {})})
        return out

    def stats(self) -> Dict[str, Any]:
        self.drain()   # read-your-writes over the staging queue
        with self._lock:
            # sealed ring slots plus one pending value per series —
            # every point a query can surface
            points = sum(len(r) for s in self._series.values()
                         for r in s["rings"]) + len(self._series)
            return {"series": len(self._series), "points": points,
                    "bytes": self._bytes,
                    "max_series": self._max_series,
                    "max_bytes": self._max_bytes,
                    "resolutions": [list(r) for r in self._resolutions],
                    "dropped_points": self._dropped_points,
                    "evicted_series": self._evicted_series}


# --------------------------------------------------- recovery auditing
# episode latencies in SECONDS (not the default ms ladder): drains ride
# multi-second grace windows, failovers tens of seconds
RECOVERY_S_BOUNDARIES = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                         100, 250, 600)

_M_DRAIN_S = rtm.histogram(
    "ray_tpu_recovery_drain_s",
    "NODE_PREEMPTING -> NODE_DRAINED latency per drain episode (s).",
    boundaries=RECOVERY_S_BOUNDARIES)
_M_FAILOVER_S = rtm.histogram(
    "ray_tpu_recovery_failover_s",
    "first failure event -> TRAIN_GANG_RECOVERY time-to-failover (s).",
    boundaries=RECOVERY_S_BOUNDARIES)
_M_HEAL_S = rtm.histogram(
    "ray_tpu_recovery_heal_s",
    "REPLICA_RETIRED -> next AUTOSCALE pool-heal latency (s).",
    boundaries=RECOVERY_S_BOUNDARIES)
_M_REROLE_S = rtm.histogram(
    "ray_tpu_recovery_rerole_s",
    "SERVE_REROLE -> SERVE_REROLE_DONE pool re-roling latency (s).",
    boundaries=RECOVERY_S_BOUNDARIES)
_M_RL_ACTOR_S = rtm.histogram(
    "ray_tpu_recovery_rl_actor_s",
    "RL_ACTOR_LOST -> RL_ACTOR_JOINED rollout-actor replacement "
    "latency per fleet slot (s).",
    boundaries=RECOVERY_S_BOUNDARIES)
_M_EPISODES = rtm.counter_family(
    "ray_tpu_recovery_episodes_total",
    "Closed recovery episodes by kind.", tag_keys=("kind",))
_M_SLO_VIOLATIONS = rtm.counter_family(
    "ray_tpu_recovery_slo_violations_total",
    "Closed episodes whose latency exceeded the recovery SLO, by kind.",
    tag_keys=("kind",))
_M_TRANSFER_FAILOVERS = rtm.counter(
    "ray_tpu_recovery_transfer_failovers_total",
    "TRANSFER_FAILOVER events folded by the recovery auditor.")
_M_LOST_STEPS = rtm.counter(
    "ray_tpu_recovery_lost_steps_total",
    "Re-executed training steps (lost work) across failover episodes.")

# episode kinds
DRAIN = "drain"
FAILOVER = "failover"
HEAL = "heal"
REROLE = "rerole"
RL_ACTOR = "rl_actor"

# recovery SLO targets are read per closed episode — rare — but the
# auditor sits on the event-put path, so ride the same generation cache
_slo_cache = (-1, 0.0, 0.0, 0.0, 0.0, 0.0)


def _slos() -> tuple:
    global _slo_cache
    gen = CONFIG.generation()
    cached = _slo_cache
    if cached[0] != gen:
        cached = (gen, CONFIG.recovery_slo_drain_s,
                  CONFIG.recovery_slo_failover_s,
                  CONFIG.recovery_slo_heal_s,
                  CONFIG.recovery_slo_rerole_s,
                  CONFIG.recovery_slo_rl_actor_s)
        _slo_cache = cached
    return cached


class RecoveryAuditor:
    """Folds the typed event stream into recovery episodes.

    ``observe(events)`` runs inside the GCS on every event-table put
    (both the legacy single-event RPC and the batched flusher path),
    AFTER the events land in the table — so episode timestamps are the
    event-plane timestamps the chaos gates previously subtracted by
    hand, and an episode can always be cross-checked against its
    ground-truth events.  The auditor must never emit cluster events
    itself (that would recurse through the put hook); it publishes
    derived values through ``ray_tpu_recovery_*`` instruments and its
    own bounded episode table.

    Episode lifecycle: an *opening* event creates an open episode keyed
    ``(kind, key)``; the matching *closing* event stamps the latency,
    classifies it against the SLO and rotates the episode into the
    bounded store (count + byte budgets; per-kind totals survive
    rotation like the event table's ``counts_by_type``)."""

    def __init__(self, max_episodes: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self._max_episodes = (CONFIG.gcs_max_recovery_episodes
                              if max_episodes is None else max_episodes)
        self._max_bytes = (CONFIG.gcs_recovery_max_bytes
                           if max_bytes is None else max_bytes)
        self._lock = threading.Lock()
        self._open: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._episodes: deque = deque()
        self._bytes = 0
        self._seq = 0
        self._dropped = 0
        self._counts: Dict[str, int] = {}
        self._violations: Dict[str, int] = {}
        self._transfer_failovers = 0
        self._transfer_by_outcome: Dict[str, int] = {}
        self._lost_steps = 0

    # ------------------------------------------------------ ingestion
    def observe(self, events: List[Dict[str, Any]]) -> None:
        for ev in events or []:
            try:
                self._observe_one(ev)
            except Exception:
                # auditing is derived data: a malformed event must never
                # break the event-put path it rides on
                pass

    def _observe_one(self, ev: Dict[str, Any]) -> None:
        etype = ev.get("type")
        if etype == "NODE_PREEMPTING":
            self._on_preempting(ev)
        elif etype == "NODE_DRAINED":
            self._on_drained(ev)
        elif etype == "OBJECT_EVACUATED":
            self._on_evacuated(ev)
        elif etype == "NODE_DEAD":
            self._on_dead(ev)
        elif etype == "TRAIN_GANG_RECOVERY":
            self._on_gang_recovery(ev)
        elif etype == "REPLICA_RETIRED":
            self._on_replica_retired(ev)
        elif etype == "AUTOSCALE":
            self._on_autoscale(ev)
        elif etype == "SERVE_REROLE":
            self._on_rerole(ev)
        elif etype == "SERVE_REROLE_DONE":
            self._on_rerole_done(ev)
        elif etype == "RL_ACTOR_LOST":
            self._on_rl_actor_lost(ev)
        elif etype == "RL_ACTOR_JOINED":
            self._on_rl_actor_joined(ev)
        elif etype == "TRANSFER_FAILOVER":
            with self._lock:
                self._transfer_failovers += 1
                outcome = str(ev.get("outcome", "unknown"))
                self._transfer_by_outcome[outcome] = \
                    self._transfer_by_outcome.get(outcome, 0) + 1
            _M_TRANSFER_FAILOVERS.inc()

    def _open_episode(self, kind: str, key: str, ev: Dict[str, Any],
                      **fields) -> Dict[str, Any]:
        with self._lock:
            ep = self._open.get((kind, key))
            if ep is not None:
                return ep   # idempotent: first opening event anchors
            self._seq += 1
            ep = {"id": f"{kind}-{self._seq}", "kind": kind, "key": key,
                  "opened_ts": ev.get("ts") or time.time(),
                  "open": True, "opening_type": ev.get("type")}
            ep.update(fields)
            self._open[(kind, key)] = ep
            return ep

    def _close_episode(self, kind: str, key: str, ev: Dict[str, Any],
                       slo_s: float, metric, **fields) -> \
            Optional[Dict[str, Any]]:
        with self._lock:
            ep = self._open.pop((kind, key), None)
            if ep is None:
                return None
            ep["closed_ts"] = ev.get("ts") or time.time()
            ep["latency_s"] = round(
                max(0.0, ep["closed_ts"] - ep["opened_ts"]), 6)
            ep["open"] = False
            ep["closing_type"] = ev.get("type")
            ep.update(fields)
            ep["slo_s"] = slo_s
            ep["violation"] = bool(slo_s > 0
                                   and ep["latency_s"] > slo_s)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            if ep["violation"]:
                self._violations[kind] = \
                    self._violations.get(kind, 0) + 1
            self._rotate_in_locked(ep)
        metric.observe(ep["latency_s"])
        _M_EPISODES.inc(kind)
        if ep["violation"]:
            _M_SLO_VIOLATIONS.inc(kind)
        return ep

    def _rotate_in_locked(self, ep: Dict[str, Any]) -> None:
        try:
            size = len(json.dumps(ep, default=str))
        except (TypeError, ValueError):
            size = 256
        ep["_size"] = size
        self._episodes.append(ep)
        self._bytes += size
        while len(self._episodes) > self._max_episodes or \
                (self._bytes > self._max_bytes and self._episodes):
            old = self._episodes.popleft()
            self._bytes -= old.get("_size", 256)
            self._dropped += 1

    # ------------------------------------------------- per-event folds
    def _on_preempting(self, ev: Dict[str, Any]) -> None:
        node = ev.get("node_id") or ""
        grace = float(ev.get("grace_s") or 0.0)
        self._open_episode(DRAIN, node, ev, node_id=node, grace_s=grace,
                           reason=ev.get("reason"), evacuated=0,
                           evacuated_bytes=0)
        # a preemption notice is also the earliest failure anchor for a
        # gang riding this node: the graceful chaos leg measures
        # time-to-failover from NODE_PREEMPTING, not NODE_DEAD
        self._open_episode(FAILOVER, node, ev, node_id=node)

    def _on_drained(self, ev: Dict[str, Any]) -> None:
        node = ev.get("node_id") or ""
        slo_drain = _slos()[1]
        with self._lock:
            ep = self._open.get((DRAIN, node))
            grace = float(ep.get("grace_s") or 0.0) if ep else 0.0
        # explicit SLO wins; otherwise the advertised grace window IS
        # the drain budget the raylet promised to finish inside
        slo = slo_drain if slo_drain > 0 else grace
        self._close_episode(
            DRAIN, node, ev, slo, _M_DRAIN_S,
            evacuated=ev.get("evacuated", 0),
            evacuated_bytes=ev.get("bytes", 0),
            failed=ev.get("failed", 0),
            raylet_duration_s=ev.get("duration_s"))

    def _on_evacuated(self, ev: Dict[str, Any]) -> None:
        node = ev.get("node_id") or ""
        with self._lock:
            ep = self._open.get((DRAIN, node))
            if ep is not None:
                ep["evacuated"] = ep.get("evacuated", 0) + 1
                ep["evacuated_bytes"] = (ep.get("evacuated_bytes", 0)
                                         + int(ev.get("bytes") or 0))

    def _on_dead(self, ev: Dict[str, Any]) -> None:
        node = ev.get("node_id") or ""
        # keep the earlier NODE_PREEMPTING anchor if one exists (the
        # graceful path); otherwise the death IS the failure instant
        self._open_episode(FAILOVER, node, ev, node_id=node,
                           actors_affected=ev.get("actors_affected"))
        # a dead node can no longer report NODE_DRAINED: close a
        # dangling drain episode as failed-by-death so it doesn't sit
        # open forever (latency = lifetime of the grace attempt)
        with self._lock:
            dangling = (DRAIN, node) in self._open
        if dangling:
            self._close_episode(DRAIN, node, ev, 0.0, _M_DRAIN_S,
                                outcome="died before drained")

    def _on_gang_recovery(self, ev: Dict[str, Any]) -> None:
        """Close the OLDEST open failover anchor: the recovery event
        carries the experiment, not the node, so the auditor pairs it
        with the longest-outstanding failure — the same convention the
        chaos gate used when subtracting timestamps by hand."""
        with self._lock:
            open_keys = sorted(
                (k for k in self._open if k[0] == FAILOVER),
                key=lambda k: self._open[k]["opened_ts"])
            key = open_keys[0][1] if open_keys else None
        lost = int(ev.get("lost_steps") or 0)
        fields = dict(
            experiment=ev.get("experiment"), attempt=ev.get("attempt"),
            reason=ev.get("reason"), downtime_s=ev.get("downtime_s"),
            resumed_from_checkpoint=ev.get("resumed_from_checkpoint"),
            lost_steps=lost, resume_step=ev.get("resume_step"),
            last_step=ev.get("last_step"))
        if key is None:
            # recovery without an observed failure event (e.g. a worker
            # crash below the node plane): still an episode — anchored
            # on the trainer's own downtime clock when it carried one
            ts = ev.get("ts") or time.time()
            downtime = float(ev.get("downtime_s") or 0.0)
            anchor = {"ts": ts - downtime, "type": "TRAIN_DOWNTIME"}
            self._open_episode(FAILOVER, f"run:{ev.get('experiment')}",
                               anchor)
            key = f"run:{ev.get('experiment')}"
        ep = self._close_episode(FAILOVER, key, ev, _slos()[2],
                                 _M_FAILOVER_S, **fields)
        if ep is not None and lost > 0:
            with self._lock:
                self._lost_steps += lost
            _M_LOST_STEPS.inc(lost)

    def _on_replica_retired(self, ev: Dict[str, Any]) -> None:
        dep = ev.get("deployment") or ""
        self._open_episode(HEAL, dep, ev, deployment=dep,
                           replica=ev.get("replica"),
                           reason=ev.get("reason"), retired=1)
        with self._lock:
            ep = self._open.get((HEAL, dep))
            if ep is not None and ep.get("replica") != ev.get("replica"):
                ep["retired"] = ep.get("retired", 1) + 1

    def _on_autoscale(self, ev: Dict[str, Any]) -> None:
        dep = ev.get("deployment") or ""
        self._close_episode(HEAL, dep, ev, _slos()[3], _M_HEAL_S,
                            old_target=ev.get("old_target"),
                            new_target=ev.get("new_target"),
                            load=ev.get("load"))

    def _on_rerole(self, ev: Dict[str, Any]) -> None:
        # keyed by the pool pair: the controller serializes re-roles per
        # pair (cooldown), so one open episode per pair is the contract
        key = f"{ev.get('src')}->{ev.get('dst')}"
        self._open_episode(REROLE, key, ev, src=ev.get("src"),
                           dst=ev.get("dst"), replica=ev.get("replica"),
                           reason=ev.get("reason"),
                           slo_kind=ev.get("slo_kind"),
                           trace_id=ev.get("trace_id"))

    def _on_rerole_done(self, ev: Dict[str, Any]) -> None:
        key = f"{ev.get('src')}->{ev.get('dst')}"
        self._close_episode(REROLE, key, ev, _slos()[4], _M_REROLE_S,
                            src_replicas=ev.get("src_replicas"),
                            dst_replicas=ev.get("dst_replicas"))

    def _on_rl_actor_lost(self, ev: Dict[str, Any]) -> None:
        # keyed per fleet slot: the executor replaces an actor in its
        # own slot, so LOST(run, slot) pairs with the next JOINED of
        # the same slot
        key = f"{ev.get('run_id')}/{ev.get('slot')}"
        self._open_episode(RL_ACTOR, key, ev, run_id=ev.get("run_id"),
                           slot=ev.get("slot"), reason=ev.get("reason"))

    def _on_rl_actor_joined(self, ev: Dict[str, Any]) -> None:
        key = f"{ev.get('run_id')}/{ev.get('slot')}"
        self._close_episode(RL_ACTOR, key, ev, _slos()[5], _M_RL_ACTOR_S,
                            weight_version=ev.get("weight_version"),
                            weight_pull_ms=ev.get("weight_pull_ms"))

    # ---------------------------------------------------------- views
    def list(self, kind: Optional[str] = None,
             include_open: bool = True,
             limit: int = 100) -> List[Dict[str, Any]]:
        """Episodes, oldest first, open ones (snapshot) at the tail."""
        with self._lock:
            closed = [dict(ep) for ep in self._episodes
                      if kind is None or ep["kind"] == kind]
            opened = [dict(ep) for ep in sorted(
                self._open.values(), key=lambda e: e["opened_ts"])
                if kind is None or ep["kind"] == kind] \
                if include_open else []
        out = closed + opened
        for ep in out:
            ep.pop("_size", None)
        return out[-max(0, limit):]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"episodes": len(self._episodes),
                    "open": len(self._open),
                    "bytes": self._bytes,
                    "max_episodes": self._max_episodes,
                    "max_bytes": self._max_bytes,
                    "dropped": self._dropped,
                    "counts_by_kind": dict(self._counts),
                    "violations_by_kind": dict(self._violations),
                    "transfer_failovers": self._transfer_failovers,
                    "transfer_by_outcome":
                        dict(self._transfer_by_outcome),
                    "lost_steps": self._lost_steps}


# --------------------------------------------------------------- doctor
_SEV_ORDER = {"ERROR": 0, "WARNING": 1, "INFO": 2}


def _finding(severity: str, category: str, summary: str,
             evidence: List[str]) -> Dict[str, Any]:
    return {"severity": severity, "category": category,
            "summary": summary, "evidence": evidence}


def build_doctor_report(snapshot: Dict[str, Any]) -> Dict[str, Any]:
    """Correlate one cross-plane snapshot into ranked findings.

    ``snapshot`` keys (all optional — the doctor degrades per missing
    plane rather than failing): ``nodes`` (node-table rows), ``events``
    (recent WARNING+ typed events), ``event_stats``, ``episodes`` +
    ``recovery_stats`` (auditor), ``step_stats`` (run rows + table
    stats), ``traces`` (SLO-violating roots), ``dossiers`` (ids or
    summaries), ``history_stats``.  Pure function of the snapshot so
    tests can feed it synthetic planes."""
    findings: List[Dict[str, Any]] = []
    nodes = snapshot.get("nodes") or []
    dead = [n for n in nodes if not n.get("alive", True)]
    draining = [n for n in nodes
                if n.get("alive", True) and n.get("draining")]
    unhealthy = [n for n in nodes
                 if n.get("alive", True) and n.get("unhealthy")]
    if dead:
        findings.append(_finding(
            "ERROR", "nodes", f"{len(dead)} dead node(s)",
            [f"node {n.get('node_id', '?')[:12]} dead"
             f" (dossier: {n.get('node_id', '?')[:12]})" for n in dead]))
    if unhealthy:
        findings.append(_finding(
            "WARNING", "nodes", f"{len(unhealthy)} unhealthy node(s)",
            [f"node {n.get('node_id', '?')[:12]}: "
             f"{', '.join(n.get('unhealthy_reasons') or ['unhealthy'])}"
             for n in unhealthy]))
    if draining:
        findings.append(_finding(
            "WARNING", "nodes", f"{len(draining)} draining node(s)",
            [f"node {n.get('node_id', '?')[:12]} draining"
             for n in draining]))

    episodes = snapshot.get("episodes") or []
    violations = [ep for ep in episodes
                  if ep.get("violation") and not ep.get("open")]
    stuck = [ep for ep in episodes if ep.get("open")]
    if violations:
        findings.append(_finding(
            "WARNING", "recovery",
            f"{len(violations)} recovery episode(s) violated their SLO",
            [f"{ep['kind']} {ep.get('id', '?')} "
             f"({ep.get('key', '?')[:12]}): "
             f"{ep.get('latency_s', 0):.2f}s > slo "
             f"{ep.get('slo_s', 0):.2f}s" for ep in violations]))
    if stuck:
        findings.append(_finding(
            "WARNING", "recovery",
            f"{len(stuck)} recovery episode(s) still open",
            [f"{ep['kind']} {ep.get('id', '?')} "
             f"({ep.get('key', '?')[:12]}) opened by "
             f"{ep.get('opening_type')}" for ep in stuck]))
    closed = [ep for ep in episodes if not ep.get("open")]
    if closed:
        worst = max(closed, key=lambda e: e.get("latency_s") or 0)
        findings.append(_finding(
            "INFO", "recovery",
            f"{len(closed)} recovery episode(s) closed",
            [f"slowest: {worst['kind']} {worst.get('id', '?')} "
             f"({worst.get('key', '?')[:12]}) "
             f"{worst.get('latency_s', 0):.2f}s"
             + (f", {worst.get('lost_steps')} step(s) re-executed"
                if worst.get("lost_steps") else "")]))

    rstats = snapshot.get("recovery_stats") or {}
    if rstats.get("transfer_failovers"):
        findings.append(_finding(
            "INFO", "recovery",
            f"{rstats['transfer_failovers']} transfer failover(s)",
            [f"outcome {k}: {v}" for k, v in sorted(
                (rstats.get("transfer_by_outcome") or {}).items())]))

    events = snapshot.get("events") or []
    by_type: Dict[str, List[dict]] = {}
    for ev in events:
        if _SEV_ORDER.get(ev.get("severity", "INFO"), 2) <= 1:
            by_type.setdefault(ev.get("type", "EVENT"), []).append(ev)
    for etype, evs in sorted(by_type.items(),
                             key=lambda kv: -len(kv[1])):
        errors = [e for e in evs if e.get("severity") == "ERROR"]
        sev = "ERROR" if errors else "WARNING"
        latest = max(evs, key=lambda e: e.get("ts") or 0)
        findings.append(_finding(
            sev, "events", f"{len(evs)} {etype} event(s)",
            [f"latest: {latest.get('message', '')[:120]}"]))

    stragglers = [e for e in events if e.get("type") == "TRAIN_STRAGGLER"]
    if stragglers:
        latest = max(stragglers, key=lambda e: e.get("ts") or 0)
        findings.append(_finding(
            "WARNING", "training",
            f"{len(stragglers)} straggler flag(s) raised",
            [f"latest: {latest.get('message', '')[:120]}"]))

    traces = snapshot.get("traces") or []
    if traces:
        findings.append(_finding(
            "WARNING", "tracing",
            f"{len(traces)} SLO-violating trace(s)",
            [f"trace {t.get('trace_id', '?')[:16]} "
             f"{t.get('route', '?')} {t.get('duration_ms', 0):.0f}ms"
             for t in traces[:5]]))

    dossiers = snapshot.get("dossiers") or []
    if dossiers:
        findings.append(_finding(
            "INFO", "dossiers", f"{len(dossiers)} open dossier(s)",
            [str(d)[:80] if not isinstance(d, dict)
             else f"{d.get('kind', '?')} {d.get('dossier_id', '')[:12]}"
                  f": {str(d.get('reason', ''))[:60]}"
             for d in dossiers[:8]]))

    hstats = snapshot.get("history_stats") or {}
    if hstats:
        findings.append(_finding(
            "INFO", "history",
            f"metrics history: {hstats.get('series', 0)} series, "
            f"{hstats.get('points', 0)} points",
            [f"{hstats.get('bytes', 0)} bytes of "
             f"{hstats.get('max_bytes', 0)} budget; "
             f"{hstats.get('dropped_points', 0)} point(s) aged out"]))

    findings.sort(key=lambda f: _SEV_ORDER.get(f["severity"], 2))
    healthy = not any(f["severity"] != "INFO" for f in findings)
    return {"generated_ts": snapshot.get("now") or time.time(),
            "healthy": healthy,
            "findings": findings,
            "counts": {
                "nodes": len(nodes), "dead_nodes": len(dead),
                "draining_nodes": len(draining),
                "episodes": len(episodes),
                "slo_violations": len(violations),
                "open_episodes": len(stuck)}}


def format_doctor_report(report: Dict[str, Any]) -> str:
    """Operator text for ``ray-tpu doctor`` (the metrics_summary
    rendering idiom: sectioned, aligned, greppable)."""
    lines = ["=== ray-tpu doctor ==="]
    c = report.get("counts", {})
    lines.append(
        f"cluster: {c.get('nodes', 0)} node(s), "
        f"{c.get('dead_nodes', 0)} dead, "
        f"{c.get('draining_nodes', 0)} draining | "
        f"recovery: {c.get('episodes', 0)} episode(s), "
        f"{c.get('slo_violations', 0)} SLO violation(s), "
        f"{c.get('open_episodes', 0)} open")
    lines.append("verdict: " + ("HEALTHY" if report.get("healthy")
                                else "ATTENTION NEEDED"))
    findings = report.get("findings") or []
    if not findings:
        lines.append("no findings.")
    for i, f in enumerate(findings, 1):
        lines.append(f"[{i}] {f['severity']:7s} {f['category']}: "
                     f"{f['summary']}")
        for ev in f.get("evidence", []):
            lines.append(f"      - {ev}")
    return "\n".join(lines)
