"""Logging setup shared by drivers, daemons, and workers.

Analog of the reference's spdlog wrapper + per-session log dir layout
(/root/reference/python/ray/_private/ray_logging.py, src/ray/util/logging.cc):
every process logs to ``<session_dir>/logs/<component>-<pid>.log`` and,
for workers, optionally mirrors stdout/stderr there so the driver-side log
monitor can tail and forward them.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s %(name)s:%(lineno)d -- %(message)s"


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"ray_tpu.{name}")


def enable_stack_dumps(session_dir: str | None) -> None:
    """SIGUSR1 -> dump every thread's Python stack to the session log dir
    (py-spy/`ray stack` analog, cf. reference python/ray/scripts `ray
    stack`): `ray-tpu stack` signals all session processes and collects
    the files. The dump file is kept open for the process's lifetime —
    faulthandler requires a stable fd."""
    if not session_dir:
        return
    import faulthandler
    import signal

    try:
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        f = open(os.path.join(log_dir, f"stack_{os.getpid()}.txt"), "w")
        faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)
    except (OSError, ValueError, AttributeError):
        pass  # debugging aid only; never fail startup over it


def setup_component_logging(component: str, session_dir: str | None = None,
                            level: int = logging.INFO) -> str | None:
    """Configure the root ray_tpu logger; returns the log file path if any."""
    logger = logging.getLogger("ray_tpu")
    logger.setLevel(level)
    logger.propagate = False
    for h in list(logger.handlers):
        logger.removeHandler(h)
    path = None
    if session_dir:
        log_dir = os.path.join(session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, f"{component}-{os.getpid()}.log")
        handler: logging.Handler = logging.FileHandler(path)
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    return path


def redirect_stdio_to(path_prefix: str) -> None:
    """Redirect this process's stdout/stderr to files (worker processes)."""
    parent = os.path.dirname(path_prefix)
    if parent:
        os.makedirs(parent, exist_ok=True)
    out = open(path_prefix + ".out", "a", buffering=1)
    err = open(path_prefix + ".err", "a", buffering=1)
    os.dup2(out.fileno(), sys.stdout.fileno())
    os.dup2(err.fileno(), sys.stderr.fileno())
