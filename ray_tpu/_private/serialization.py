"""Object serialization: pickle-5 with out-of-band buffers.

TPU-native analog of the reference's SerializationContext
(/root/reference/python/ray/_private/serialization.py:92; out-of-band buffer
handling at :191-204).  Wire format of a serialized object:

    [u32 meta_len][meta: msgpack {nbuf, lens, error?}] [pickled payload] [buf0][buf1]...

Large contiguous buffers (numpy arrays, bytes) are carried out-of-band via
``pickle.protocol=5`` buffer callbacks, so a store ``get`` can reconstruct
numpy arrays as zero-copy views over shared memory.  JAX arrays are converted
to numpy on serialize (device arrays never transit the object store — on TPU
they stay device-resident and move via in-graph collectives, SURVEY.md §5).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle
import msgpack

_HEADER = struct.Struct("<I")

# Error sentinel types stored as object payloads (cf. reference RayError
# hierarchy, python/ray/exceptions.py).
ERROR_TASK = 1
ERROR_ACTOR_DIED = 2
ERROR_WORKER_DIED = 3
ERROR_OBJECT_LOST = 4
ERROR_TASK_CANCELLED = 5
ERROR_OOM = 6


def _identity(x):
    return x


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, buffers: List[pickle.PickleBuffer]):
        super().__init__(file, protocol=5,
                         buffer_callback=lambda b: buffers.append(b) or False)

    def reducer_override(self, obj):
        # Device arrays -> host numpy before pickling.  The numpy array is
        # handed back to *this* pickler so its buffer rides out-of-band.
        tn = type(obj).__module__
        if tn.startswith("jaxlib") or tn.startswith("jax."):
            try:
                import jax
                if isinstance(obj, jax.Array):
                    import numpy as np
                    return (_identity, (np.asarray(obj),))
            except ImportError:
                pass
        return NotImplemented


def serialize(value: Any, error_type: int = 0) -> Tuple[bytes, List[memoryview]]:
    """Returns (header_and_payload, out_of_band_buffers)."""
    import io
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    _Pickler(f, buffers).dump(value)
    payload = f.getvalue()
    views = [b.raw() for b in buffers]
    meta = msgpack.packb({
        "n": len(views),
        "lens": [len(v) for v in views],
        "plen": len(payload),
        "err": error_type,
    })
    head = _HEADER.pack(len(meta)) + meta
    return head + payload, views


def serialized_size(head_payload: bytes, views: List[memoryview]) -> int:
    return len(head_payload) + sum(len(v) for v in views)


def to_flat_bytes(head_payload: bytes, views: List[memoryview]) -> bytes:
    out = bytearray(head_payload)
    for v in views:
        out += v
    return bytes(out)


def write_into(buf: memoryview, head_payload: bytes, views: List[memoryview]) -> int:
    """Write the full serialized object into a preallocated buffer."""
    off = len(head_payload)
    buf[:off] = head_payload
    for v in views:
        n = len(v)
        buf[off:off + n] = v
        off += n
    return off


def deserialize(buf: memoryview | bytes) -> Any:
    """Reconstruct from one contiguous buffer; numpy views stay zero-copy."""
    return deserialize_with_viewinfo(buf)[0]


def deserialize_with_viewinfo(buf: memoryview | bytes) -> Tuple[Any, bool]:
    """Reconstruct from one contiguous buffer; returns (value,
    holds_views).  ``holds_views`` is True when the payload carried
    out-of-band buffers — the deserialized value (numpy arrays etc.) may
    hold zero-copy views into ``buf``, so a shared-memory caller must
    keep the segment pinned; when False the value is self-contained and
    the pin can be released immediately."""
    buf = memoryview(buf)
    (meta_len,) = _HEADER.unpack(bytes(buf[:_HEADER.size]))
    off = _HEADER.size
    meta = msgpack.unpackb(bytes(buf[off:off + meta_len]))
    off += meta_len
    payload = buf[off:off + meta["plen"]]
    off += meta["plen"]
    oob = []
    for n in meta["lens"]:
        oob.append(buf[off:off + n])
        off += n
    value = pickle.loads(payload, buffers=oob)
    if meta.get("err"):
        raise value
    return value, bool(meta["lens"])


def error_type_of(buf: memoryview | bytes) -> int:
    buf = memoryview(buf)
    (meta_len,) = _HEADER.unpack(bytes(buf[:_HEADER.size]))
    meta = msgpack.unpackb(bytes(buf[_HEADER.size:_HEADER.size + meta_len]))
    return meta.get("err", 0)
