"""Always-on, low-overhead runtime telemetry for the hot paths.

The control-plane fast paths (docs/rpc_fastpath.md) and streaming
generators (docs/streaming_generators.md) made the runtime fast but
opaque: regressions were only visible when someone manually reran
``collect_microbench``.  This module is the counterpart of the
reference's internal stats/metrics layer (``metric_defs.cc`` +
``metrics_agent.py``): every daemon and worker records a fixed set of
counters and latency histograms on its hot loops, and a single
background flusher publishes snapshots to the GCS KV ``metrics/``
namespace — the exact wire format user metrics (util/metrics.py) use,
so ``query_metrics``, the dashboard ``/metrics`` Prometheus endpoint
and ``experimental.state.list_metrics()`` pick runtime metrics up for
free.

Record-path design (the whole point of not reusing util/metrics.py):

* instruments are **preallocated** and bound into module/instance
  attributes by their call sites; the record path is attribute
  arithmetic only — ``Counter.inc`` is ``self.value += n``,
  ``Histogram.observe`` is one ``bisect`` into preallocated bucket
  slots.  No dict lookup, no lock, no tag merging per record.  Under
  the GIL a lost update is possible but rare and harmless for
  monitoring data (the reference's C++ stats make the same relaxed-
  consistency tradeoff).
* timers are coarse monotonic stamps (``time.monotonic``); callers use
  ``observe_since(t0)`` which records milliseconds.
* per-label families (per RPC method, per task function) pay one dict
  hit on ``observe(label, v)``; call sites that can bind the label
  once use ``get(label)`` and keep the plain Histogram.

Kill switch: ``CONFIG.telemetry_enabled`` (env override
``RAY_TPU_TELEMETRY=0``).  When off, the instrument getters hand back
shared no-op stubs, so an instrumented code path costs one no-op
method call and the flusher never starts.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.config import CONFIG

now = time.monotonic

# default latency boundaries, in milliseconds: sub-100us RPC dispatches
# up to 10 s task executions land in distinct buckets
DEFAULT_MS_BOUNDARIES: Tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)
# small-integer boundaries for batch/queue-size distributions
COUNT_BOUNDARIES: Tuple[float, ...] = (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 64)


def enabled() -> bool:
    """Kill switch: RAY_TPU_TELEMETRY env wins, then the config flag."""
    raw = os.environ.get("RAY_TPU_TELEMETRY")
    if raw is not None:
        return raw.strip().lower() not in ("0", "false", "no", "off")
    return CONFIG.telemetry_enabled


class Counter:
    """Monotonic counter; ``inc`` is a bare attribute add (no lock)."""

    __slots__ = ("name", "description", "value")
    kind = "counter"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def _payload(self) -> dict:
        return {"type": "counter", "description": self.description,
                "values": {"{}": self.value}, "ts": time.time()}


class CounterFamily:
    """Per-label-set counters (serve SLO verdicts by pool+dimension).

    The Counter sibling of HistogramFamily: ``inc(labels)`` pays one
    dict hit, where ``labels`` is a tuple matching ``tag_keys`` (or a
    bare string for a single key).  Label sets are bounded
    (``max_labels``) like the histogram family."""

    kind = "counter"

    def __init__(self, name: str, description: str = "", *,
                 tag_keys=("label",), max_labels: int = 256):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self.max_labels = max_labels
        self._items: Dict[tuple, Counter] = {}
        self._lock = threading.Lock()
        self._overflow: Optional[Counter] = None

    def get(self, labels) -> Counter:
        if isinstance(labels, str):
            labels = (labels,)
        labels = tuple(labels)
        c = self._items.get(labels)
        if c is None:
            with self._lock:
                c = self._items.get(labels)
                if c is None:
                    if len(self._items) >= self.max_labels:
                        if self._overflow is None:
                            self._overflow = Counter(self.name,
                                                     self.description)
                            self._items[("__other__",) * len(
                                self.tag_keys)] = self._overflow
                        return self._overflow
                    c = Counter(self.name, self.description)
                    self._items[labels] = c
        return c

    def inc(self, labels, n: float = 1.0) -> None:
        self.get(labels).inc(n)

    def labels(self) -> List[tuple]:
        with self._lock:
            return list(self._items)

    def _payload(self) -> dict:
        with self._lock:
            items = list(self._items.items())
        return {"type": "counter", "description": self.description,
                "values": {json.dumps(dict(zip(self.tag_keys, labels))):
                           c.value for labels, c in items},
                "ts": time.time()}


class Gauge:
    """Point-in-time value.  ``watermark`` gauges track a high-water
    mark via ``set_max`` and are reset to 0 after each flush, so every
    scrape interval reports its own peak (queue depths)."""

    __slots__ = ("name", "description", "value", "watermark")
    kind = "gauge"

    def __init__(self, name: str, description: str = "",
                 watermark: bool = False):
        self.name = name
        self.description = description
        self.value = 0.0
        self.watermark = watermark

    def set(self, v: float) -> None:
        self.value = v

    def set_max(self, v: float) -> None:
        if v > self.value:
            self.value = v

    def _payload(self) -> dict:
        return {"type": "gauge", "description": self.description,
                "values": {"{}": self.value}, "ts": time.time()}


class Histogram:
    """Latency/size distribution over preallocated bucket slots.

    ``observe`` is one C-level bisect plus three attribute adds; the
    final slot is the +Inf overflow bucket.  ``sum``/``count`` ride
    along so the Prometheus exposition can emit ``_sum``/``_count``."""

    __slots__ = ("name", "description", "boundaries", "counts", "sum",
                 "count")
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Iterable[float] = DEFAULT_MS_BOUNDARIES):
        self.name = name
        self.description = description
        self.boundaries = tuple(sorted(boundaries))
        self.counts = [0] * (len(self.boundaries) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.boundaries, v)] += 1
        self.sum += v
        self.count += 1

    def observe_since(self, t0: float) -> None:
        """Record elapsed milliseconds since monotonic stamp ``t0``."""
        self.observe((now() - t0) * 1000.0)

    def _bucket_dict(self) -> dict:
        out = {}
        for b, c in zip(self.boundaries, self.counts):
            if c:
                out[repr(float(b))] = c
        if self.counts[-1]:
            out["+Inf"] = self.counts[-1]
        return out

    def _value(self) -> dict:
        return {"buckets": self._bucket_dict(), "sum": self.sum,
                "count": self.count}

    def _payload(self) -> dict:
        return {"type": "histogram", "description": self.description,
                "values": {"{}": self._value()}, "ts": time.time()}


class HistogramFamily:
    """Per-label histograms (per RPC method, per task function).

    ``observe(label, v)`` pays one dict hit; ``get(label)`` returns the
    bound Histogram for call sites that can cache it.  Label sets are
    bounded (``max_labels``) so a pathological workload — a fresh
    closure name per task — can't grow the family without bound."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "", *,
                 tag_key: str = "method",
                 boundaries: Iterable[float] = DEFAULT_MS_BOUNDARIES,
                 max_labels: int = 256):
        self.name = name
        self.description = description
        self.tag_key = tag_key
        self.boundaries = tuple(sorted(boundaries))
        self.max_labels = max_labels
        self._items: Dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._overflow: Optional[Histogram] = None

    def get(self, label: str) -> Histogram:
        h = self._items.get(label)
        if h is None:
            with self._lock:
                h = self._items.get(label)
                if h is None:
                    if len(self._items) >= self.max_labels:
                        if self._overflow is None:
                            self._overflow = Histogram(
                                self.name, self.description,
                                self.boundaries)
                            self._items["__other__"] = self._overflow
                        return self._overflow
                    h = Histogram(self.name, self.description,
                                  self.boundaries)
                    self._items[label] = h
        return h

    def observe(self, label: str, v: float) -> None:
        self.get(label).observe(v)

    def observe_since(self, label: str, t0: float) -> None:
        self.get(label).observe((now() - t0) * 1000.0)

    def labels(self) -> List[str]:
        with self._lock:
            return list(self._items)

    def _payload(self) -> dict:
        with self._lock:
            items = list(self._items.items())
        return {"type": "histogram", "description": self.description,
                "values": {json.dumps({self.tag_key: label}): h._value()
                           for label, h in items},
                "ts": time.time()}


class _Noop:
    """Shared stub handed out when telemetry is disabled: every
    instrument method is a no-op, ``get`` returns itself so bound-label
    call sites stay one no-op call too."""

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def set_max(self, v: float) -> None:
        pass

    def observe(self, *a) -> None:
        pass

    def observe_since(self, *a) -> None:
        pass

    def get(self, label: str) -> "_Noop":
        return self

    def labels(self) -> list:
        return []


NOOP = _Noop()

_lock = threading.Lock()
_instruments: Dict[str, Any] = {}
_callbacks: Dict[str, Tuple[str, Callable[[], float]]] = {}
_sink: Optional[Callable[[str, bytes], Any]] = None
_ident = f"proc-{os.getpid()}"
_flusher: Optional[threading.Thread] = None
_flush_wake = threading.Event()


def _register(name: str, factory: Callable[[], Any]):
    # raylint: disable=kill-switch -- binding-time only: instruments resolve once at import; the record path never re-reads the flag
    if not enabled():
        return NOOP
    with _lock:
        inst = _instruments.get(name)
        if inst is None:
            inst = _instruments[name] = factory()
        return inst


def counter(name: str, description: str = ""):
    return _register(name, lambda: Counter(name, description))


def gauge(name: str, description: str = "", *, watermark: bool = False):
    return _register(name,
                     lambda: Gauge(name, description, watermark=watermark))


def histogram(name: str, description: str = "",
              boundaries: Iterable[float] = DEFAULT_MS_BOUNDARIES):
    return _register(name, lambda: Histogram(name, description, boundaries))


def histogram_family(name: str, description: str = "", *,
                     tag_key: str = "method",
                     boundaries: Iterable[float] = DEFAULT_MS_BOUNDARIES):
    return _register(name, lambda: HistogramFamily(
        name, description, tag_key=tag_key, boundaries=boundaries))


def counter_family(name: str, description: str = "", *,
                   tag_keys=("label",)):
    return _register(name, lambda: CounterFamily(
        name, description, tag_keys=tag_keys))


def gauge_callback(name: str, description: str,
                   fn: Callable[[], float]) -> None:
    """Register a gauge polled at flush/snapshot time (pool sizes, pin
    counts): zero hot-path cost, always-current value.  Re-registering
    a name replaces the callback (fresh CoreWorker per init())."""
    # raylint: disable=kill-switch -- binding-time only: callbacks register once per owner, polled by the flusher
    if not enabled():
        return
    with _lock:
        _callbacks[name] = (description, fn)


def remove_gauge_callback(name: str,
                          fn: Optional[Callable[[], float]] = None) -> None:
    """Drop a polled gauge at owner shutdown.  With ``fn`` given, only
    removes the entry if it is still the caller's own registration — a
    newer owner's replacement callback is left alone."""
    with _lock:
        cur = _callbacks.get(name)
        if cur is not None and (fn is None or cur[1] is fn):
            _callbacks.pop(name, None)


def snapshot(reset_watermarks: bool = False) -> Dict[str, dict]:
    """Local process snapshot in the KV wire format (tests, debugging).

    ``reset_watermarks`` is the flusher's contract: watermark gauges
    report per-interval peaks, so only the publishing path may zero
    them — a debugging ``snapshot()`` between flusher ticks must not
    eat the interval's high-water mark."""
    out: Dict[str, dict] = {}
    with _lock:
        insts = list(_instruments.items())
        cbs = list(_callbacks.items())
    for name, inst in insts:
        out[name] = inst._payload()
        if reset_watermarks and isinstance(inst, Gauge) and inst.watermark:
            inst.value = 0.0
    for name, (desc, fn) in cbs:
        try:
            v = float(fn())
        except Exception:
            continue
        out[name] = {"type": "gauge", "description": desc,
                     "values": {"{}": v}, "ts": time.time()}
    return out


def attach(sink: Callable[[str, bytes], Any], ident: str) -> None:
    """Bind this process's flusher to a KV sink (``kv_put``-shaped) and
    identity segment; starts the background flusher on first call.
    Re-attaching (a fresh init() in the same process) replaces both."""
    global _sink, _ident
    _sink = sink
    _ident = ident or _ident
    # a fresh sink means a fresh KV (new cluster): the dirty-skip cache
    # must not suppress the first publication of unchanged metrics
    _last_sent.clear()
    # raylint: disable=kill-switch -- attach() runs once per init(); the flusher it may start ticks on its own clock
    if enabled():
        _ensure_flusher()


def detach(sink: Optional[Callable[[str, bytes], Any]] = None) -> None:
    """Unbind the flusher's sink at owner shutdown so the closed GCS
    client (and everything its bound method pins — caches, sockets) can
    be collected and the flusher stops issuing doomed RPCs.  With
    ``sink`` given, only detaches if it is still the active one — a
    newer owner's attach is left in place."""
    global _sink
    if sink is None or _sink == sink:
        _sink = None


def _ensure_flusher() -> None:
    global _flusher
    if _flusher is not None and _flusher.is_alive():
        return
    with _lock:
        if _flusher is not None and _flusher.is_alive():
            return
        _flusher = threading.Thread(target=_flush_loop, daemon=True,
                                    name="runtime-metrics-flush")
        _flusher.start()


# consumers treat a metrics key whose payload ts is older than this as
# belonging to a dead process (GCS sweeper deletes them)
METRICS_STALE_AFTER_S = 120.0


def _flush_loop() -> None:
    ticks = 0
    while True:
        period = max(0.05, CONFIG.telemetry_flush_interval_ms / 1000.0)
        _flush_wake.wait(period)
        _flush_wake.clear()
        ticks += 1
        # periodically drop the dirty-skip cache so even unchanged
        # metrics refresh their ``ts`` — that freshness is what lets
        # the GCS sweeper distinguish live processes from dead ones.
        # Derived from the staleness bound (>= 3 refreshes inside it)
        # so a user-raised flush interval can't make live processes'
        # idle metrics look stale.
        refresh_every = max(1, int(METRICS_STALE_AFTER_S / (3.0 * period)))
        if ticks % refresh_every == 0:
            _last_sent.clear()
        flush_now()


# last serialized value per metric (ts stripped): unchanged metrics are
# not re-sent, so idle processes cost the GCS ~zero kv traffic and busy
# ones pay one RPC per *changed* metric per interval
_last_sent: Dict[str, bytes] = {}


def flush_now() -> None:
    """Push one snapshot through the sink (flusher tick; tests call it
    directly to avoid waiting out the interval).  Never raises: the
    sink dying (GCS teardown) must not take the process with it.
    Watermark gauges are only reset once every send succeeded — a peak
    that coincided with a sink outage is re-published next tick, not
    silently dropped."""
    sink = _sink
    if sink is None:
        return
    ok = True
    for name, payload in snapshot().items():
        ts = payload.pop("ts", None)
        body = json.dumps(payload).encode()
        if _last_sent.get(name) == body:
            continue
        payload["ts"] = ts
        # self-mark: only runtime payloads get ts keep-alives (the
        # _flush_loop refresh cadence), so only they are eligible for
        # the GCS staleness sweep — user metrics flush on record and
        # an idle live process's once-set gauge must never be swept
        payload["runtime"] = True
        try:
            sink(f"metrics/{name}/{_ident}",
                 json.dumps(payload).encode())
        except Exception:
            ok = False  # sink gone; retry whole snapshot next tick
            break
        _last_sent[name] = body
    if ok:
        _reset_watermarks()


def _reset_watermarks() -> None:
    with _lock:
        for inst in _instruments.values():
            if isinstance(inst, Gauge) and inst.watermark:
                inst.value = 0.0


def _reset_for_tests() -> None:
    """Drop all registered instruments/callbacks (unit-test isolation)."""
    global _sink
    with _lock:
        _instruments.clear()
        _callbacks.clear()
    _sink = None


# ------------------------------------------------------------ exposition
def _fmt_tags(tags: Dict[str, Any]) -> str:
    return ",".join(f'{k}="{v}"' for k, v in sorted(tags.items()))


def _hist_lines(lines: List[str], name: str, tags: Dict[str, Any],
                rec: dict) -> None:
    buckets = rec.get("buckets", {}) or {}
    total = int(rec.get("count", 0))
    cum = 0
    for le in sorted((k for k in buckets if k not in ("+Inf", "inf")),
                     key=float):
        cum += int(buckets[le])
        lines.append(
            f"{name}_bucket{{{_fmt_tags(dict(tags, le=repr(float(le))))}}}"
            f" {cum}")
    lines.append(
        f'{name}_bucket{{{_fmt_tags(dict(tags, le="+Inf"))}}} {total}')
    lines.append(f"{name}_count{{{_fmt_tags(tags)}}} {total}")
    lines.append(f"{name}_sum{{{_fmt_tags(tags)}}} {rec.get('sum', 0.0)}")


def prometheus_exposition(entries: Iterable[Tuple[str, str, dict]]) -> str:
    """Render KV metric payloads as conformant Prometheus text.

    ``entries``: (metric name, worker/process ident, payload) triples in
    the shared wire format.  Histograms become cumulative
    ``<name>_bucket{le=...}`` series (always including ``+Inf``) plus
    ``<name>_count``/``<name>_sum`` — the conformant shape Prometheus
    clients expect, instead of raw per-bucket counts tagged ``le`` on
    the bare metric name."""
    lines: List[str] = []
    seen = set()
    for name, worker, data in entries:
        mtype = data.get("type", "untyped")
        if mtype not in ("counter", "gauge", "histogram"):
            mtype = "untyped"
        if name not in seen:
            seen.add(name)
            desc = (data.get("description") or "").replace("\n", " ")
            if desc:
                lines.append(f"# HELP {name} {desc}")
            lines.append(f"# TYPE {name} {mtype}")
        for tagjson, value in (data.get("values") or {}).items():
            try:
                tags = dict(json.loads(tagjson))
            except (ValueError, TypeError):
                tags = {}
            tags["worker"] = worker
            if mtype == "histogram" and isinstance(value, dict):
                _hist_lines(lines, name, tags, value)
            else:
                lines.append(f"{name}{{{_fmt_tags(tags)}}} {value}")
    return "\n".join(lines)
