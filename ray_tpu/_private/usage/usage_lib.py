"""Usage-stats collection (local-only).

Analog of /root/reference/python/ray/_private/usage/usage_lib.py: the
reference collects cluster metadata and (opt-out) uploads a ping. TPU pods
run with zero egress, so this implementation only ever writes the report
to the session directory — there is no network path, by design. Opt out
entirely with RAY_TPU_USAGE_STATS_ENABLED=0.
"""

from __future__ import annotations

import json
import os
import platform
import time
from typing import Any, Dict


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def collect_usage_payload(gcs=None) -> Dict[str, Any]:
    import ray_tpu
    payload: Dict[str, Any] = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collect_time": time.time(),
    }
    try:
        import jax
        payload["jax_version"] = jax.__version__
        payload["device_kinds"] = sorted(
            {getattr(d, "device_kind", d.platform) for d in jax.devices()})
    except Exception:
        pass
    if gcs is not None:
        try:
            nodes = gcs.call("list_nodes", timeout=5)
            alive = [n for n in nodes if n.get("alive")]
            payload["num_nodes"] = len(alive)
            payload["total_resources"] = {
                r: sum(n["resources"].get(r, 0) for n in alive)
                for r in ("CPU", "TPU")}
        except Exception:
            pass
    return payload


def record_usage_report(session_dir: str, gcs=None) -> str:
    """Write the report file; returns its path ('' when disabled)."""
    if not usage_stats_enabled() or not session_dir:
        return ""
    path = os.path.join(session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(collect_usage_payload(gcs), f, indent=1)
    except OSError:
        return ""
    return path
