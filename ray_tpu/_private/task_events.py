"""Task state-transition events: per-worker buffer -> GCS task table.

Analog of the reference's TaskEventBuffer
(/root/reference/src/ray/core_worker/task_event_buffer.h:48): every worker
batches per-task state transitions and periodically flushes them to the GCS
(`TaskInfoGcsService`, gcs_service.proto:635), powering `list tasks`,
`summary`, and the Chrome-trace timeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu._private.config import CONFIG

# Task lifecycle states (cf. reference common.proto TaskStatus).
SUBMITTED = "SUBMITTED"
PENDING_NODE_ASSIGNMENT = "PENDING_NODE_ASSIGNMENT"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"
# instant markers (timeline dots, not lifecycle transitions): they never
# update a record's state — a streaming task stays RUNNING while its
# per-yield STREAM_ITEM instants accumulate, a PULL (one inter-node
# object transfer for the task's output, docs/object_transfer.md) rides
# whatever lifecycle state the task is in, a COLLECTIVE (one host-
# collective op on a rank's synthetic ``col-<group>-r<rank>`` record,
# docs/collective.md) never has a lifecycle at all, and a HANDOFF (one
# export/import leg of a disaggregated-serving KV handoff on a
# synthetic ``handoff-<object>`` record, docs/serve_disagg.md) likewise.
# A STEP (one clocked train step with its phase breakdown on a rank's
# synthetic ``step-<run>-r<rank>`` record, docs/observability.md) is
# the training-performance-plane sibling of COLLECTIVE/HANDOFF.
STREAM_ITEM = "STREAM_ITEM"
PULL = "PULL"
COLLECTIVE = "COLLECTIVE"
HANDOFF = "HANDOFF"
STEP = "STEP"
_INSTANT_STATES = frozenset({STREAM_ITEM, PULL, COLLECTIVE, HANDOFF, STEP})

_STATE_RANK = {SUBMITTED: 1, PENDING_NODE_ASSIGNMENT: 2, RUNNING: 3,
               FINISHED: 4, FAILED: 4}

# per-record event-list bound: long streams / chatty spans must not grow
# one task's record without limit (the first and last halves survive)
_EVENTS_PER_TASK_CAP = 512


class TaskEventBuffer:
    """Thread-safe ring buffer of task events with a periodic GCS flusher.

    Records stay available locally (``snapshot()``) even when GCS export is
    disabled (no gcs client, e.g. unit tests driving a bare CoreWorker).
    """

    def __init__(self, gcs=None, *, job_id: str = "", node_id: str = "",
                 worker_id: str = ""):
        self._gcs = gcs
        self._defaults = {"job_id": job_id, "node_id": node_id,
                          "worker_id": worker_id}
        self._buf: deque = deque(maxlen=CONFIG.task_events_buffer_size)
        self._unflushed: List[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def record(self, task_id: str, state: str, *, name: str = "",
               **extra: Any) -> None:
        if self._stop.is_set():
            return  # stopped: a late event must not restart the flusher
        ev = {"task_id": task_id, "state": state, "name": name,
              "ts": time.time()}
        ev.update(self._defaults)
        ev.update(extra)
        with self._lock:
            self._buf.append(ev)
            if self._gcs is not None:
                self._unflushed.append(ev)
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._flush_loop, daemon=True,
                        name="task-events-flush")
                    self._thread.start()

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._buf)

    def flush(self) -> None:
        with self._lock:
            batch, self._unflushed = self._unflushed, []
        if not batch or self._gcs is None:
            return
        try:
            self._gcs.call("task_events_put", {"events": batch}, timeout=5)
        except Exception:
            # GCS going away must never take a worker down with it; events
            # are best-effort observability data.
            with self._lock:
                if len(self._unflushed) < CONFIG.task_events_buffer_size:
                    self._unflushed = batch + self._unflushed

    def _flush_loop(self) -> None:
        period = CONFIG.task_events_flush_interval_ms / 1000.0
        while not self._stop.wait(period):
            self.flush()

    def stop(self) -> None:
        """Idempotent shutdown: stop the flusher, push the tail batch,
        and JOIN the flush thread — without the join, a final in-flight
        ``flush()`` races this one for the same batch and can re-queue
        events into a buffer nobody will ever drain again."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self.flush()


class GcsTaskTable:
    """GCS-side task table: merges event batches into per-task records.

    Bounded at ``CONFIG.gcs_max_task_events`` tasks; oldest terminal tasks
    are evicted first (cf. reference GcsTaskManager's task-event GC).
    """

    def __init__(self):
        self._tasks: Dict[str, Dict[str, Any]] = {}
        self._order: deque = deque()  # task ids in first-seen order
        self._lock = threading.Lock()

    def put_events(self, events: List[dict]) -> int:
        dropped = 0
        with self._lock:
            for ev in events:
                tid = ev["task_id"]
                rec = self._tasks.get(tid)
                if rec is None:
                    rec = {"task_id": tid, "name": ev.get("name", ""),
                           "job_id": ev.get("job_id", ""),
                           "state": "", "events": []}
                    self._tasks[tid] = rec
                    self._order.append(tid)
                for field in ("name", "job_id", "actor_id", "func_or_class",
                              "error_type", "trace_id"):
                    if ev.get(field):
                        rec[field] = ev[field]
                # execution attribution: node/worker come from the executing
                # worker's RUNNING event; the owner's SUBMITTED/FINISHED
                # events carry the *driver's* ids and must not stomp them
                if ev["state"] == RUNNING or "worker_id" not in rec:
                    for field in ("node_id", "worker_id"):
                        if ev.get(field):
                            rec[field] = ev[field]
                # out-of-order delivery: a worker's RUNNING may arrive after
                # the owner's FINISHED (independent flush clocks) — never let
                # a non-terminal state overwrite a terminal one.  Instant
                # markers (STREAM_ITEM) never touch the state at all.
                if ev["state"] not in _INSTANT_STATES:
                    rank = _STATE_RANK.get(ev["state"], 0)
                    if rank >= _STATE_RANK.get(rec["state"], -1):
                        rec["state"] = ev["state"]
                entry = {"state": ev["state"], "ts": ev["ts"]}
                if "index" in ev:   # per-yield stream instants
                    entry["index"] = ev["index"]
                for field in ("dur_ms", "bytes", "nsources", "object_id",
                              "node_id", "worker_id", "op", "algo",
                              "world", "stage", "npages", "step",
                              "phases", "trace_id"):
                    if field in ev:  # per-pull transfer / per-op
                        # collective / KV-handoff / train-step slices
                        # (node/worker = the pulling / participating
                        # process, not a producer task; STEP carries a
                        # per-step trace_id + phase-duration dict)
                        entry[field] = ev[field]
                rec["events"].append(entry)
                rec["events"].sort(key=lambda e: e["ts"])
                if len(rec["events"]) > _EVENTS_PER_TASK_CAP:
                    half = _EVENTS_PER_TASK_CAP // 2
                    rec["events"] = (rec["events"][:half] +
                                     rec["events"][-half:])
                    rec["events_truncated"] = True
                if ev["state"] == SUBMITTED:
                    rec["creation_time"] = ev["ts"]
                elif ev["state"] == RUNNING:
                    rec["start_time"] = ev["ts"]
                elif ev["state"] in (FINISHED, FAILED):
                    rec["end_time"] = ev["ts"]
            # Eviction scans PAST live entries (bounded) instead of
            # stopping at the first one: a long-running task at the head
            # of first-seen order used to re-append itself and break,
            # blocking eviction of every terminal task queued behind it —
            # the table then grew far beyond gcs_max_task_events.
            cap = CONFIG.gcs_max_task_events
            spared = 0
            max_spared = min(len(self._order), 256)
            while len(self._tasks) > cap and self._order:
                victim = self._order.popleft()
                rec = self._tasks.get(victim)
                if rec is None:
                    continue
                # records whose state never left "" carry only instant
                # markers — the synthetic ``handoff-<object>`` /
                # ``col-<group>-r<rank>`` rows.  They have no lifecycle
                # to finish, so they must be evictable like terminal
                # tasks: sparing them let a long-lived serve app grow
                # the table to 2x cap in handoff rows that never die.
                if rec["state"] in (FINISHED, FAILED, "") or \
                        len(self._tasks) > 2 * cap:
                    del self._tasks[victim]
                    dropped += 1
                else:
                    self._order.append(victim)  # still live; rotate past
                    spared += 1
                    if spared >= max_spared:
                        break  # everything scanned is live: give up for now
        return dropped

    def list(self, *, job_id: Optional[str] = None,
             state: Optional[str] = None, name: Optional[str] = None,
             limit: int = 10000) -> List[dict]:
        with self._lock:
            out = []
            for rec in self._tasks.values():
                if job_id and rec.get("job_id") != job_id:
                    continue
                if state and rec.get("state") != state:
                    continue
                if name and rec.get("name") != name:
                    continue
                out.append({k: (list(v) if k == "events" else v)
                            for k, v in rec.items()})
                if len(out) >= limit:
                    break
            return out
