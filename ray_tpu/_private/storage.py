"""Pluggable URI storage layer — one seam, three consumers.

The reference scatters remote-storage access across
``air/_internal/remote_storage.py:177/195`` (upload_to_uri/download_from_uri
over pyarrow filesystems), ``tune/syncer.py:185`` (checkpoint sync), and
``_private/external_storage.py:72`` (object spilling to S3/disk).  Here all
three consumers — Tune/AIR checkpoint sync, ``data.read_*``/``write_*``, and
raylet spill targets — go through this module, keyed by URI scheme:

- ``file://`` (or a bare path): the local filesystem.
- ``mock://``: an in-process memory store for tests, with optional
  deterministic fault injection (``external_storage.py:587/608``
  UnstableFileStorage/SlowFileStorage analog via :class:`FlakyStorage`).
- ``gs://``: Google Cloud Storage, behind an optional import (not in the
  hermetic image; raises a clear error if unavailable).

Everything is byte-oriented: small API (read/write/delete/exists/list), no
filesystem handles leak across the seam, so a backend can be swapped under
spilling without touching raylet logic.
"""
from __future__ import annotations

import io
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "is_uri", "parse_uri", "join_uri", "get_storage", "register_storage",
    "read_bytes", "write_bytes", "delete_uri", "exists", "list_prefix",
    "upload_dir", "download_dir", "Storage", "FileStorage", "MemoryStorage",
    "FlakyStorage",
]


def is_uri(path: str) -> bool:
    return "://" in path


def parse_uri(uri: str) -> Tuple[str, str]:
    """-> (scheme, key). A bare path is the ``file`` scheme."""
    if "://" not in uri:
        return "file", uri
    scheme, _, rest = uri.partition("://")
    return scheme, rest


def join_uri(base: str, *parts: str) -> str:
    out = base.rstrip("/")
    for p in parts:
        out += "/" + str(p).strip("/")
    return out


class Storage:
    """Byte-level storage under one URI scheme.

    ``key`` arguments are the URI with the scheme stripped
    (``parse_uri(uri)[1]``); helpers at module level accept full URIs.
    """

    def write_bytes(self, key: str, data) -> None:
        """Atomic: a concurrent read sees the old value or the new one,
        never a torn write. ``data`` is bytes or any buffer-protocol
        object (spilling passes shm memoryviews to avoid heap copies)."""
        raise NotImplementedError

    def read_bytes(self, key: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        """Raises FileNotFoundError when absent."""
        raise NotImplementedError

    def delete(self, key: str, missing_ok: bool = True) -> bool:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def list_prefix(self, key: str) -> List[str]:
        """All keys under a directory-like prefix, relative to it."""
        raise NotImplementedError


class FileStorage(Storage):
    def write_bytes(self, key: str, data: bytes) -> None:
        d = os.path.dirname(key)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{key}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, key)

    def read_bytes(self, key: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        with open(key, "rb") as f:
            if offset:
                f.seek(offset)
            return f.read(-1 if length is None else length)

    def delete(self, key: str, missing_ok: bool = True) -> bool:
        try:
            os.unlink(key)
            return True
        except FileNotFoundError:
            if not missing_ok:
                raise
            return False

    def exists(self, key: str) -> bool:
        return os.path.exists(key)

    def list_prefix(self, key: str) -> List[str]:
        root = key.rstrip("/")
        out = []
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                full = os.path.join(dirpath, fn)
                out.append(os.path.relpath(full, root))
        return sorted(out)


class MemoryStorage(Storage):
    """In-process ``mock://`` store; per-process global namespace so a
    writer and reader in the same process (the common test shape: one
    driver, or spilling inside one raylet) share state."""

    _data: Dict[str, bytes] = {}
    _lock = threading.Lock()

    def write_bytes(self, key: str, data: bytes) -> None:
        with MemoryStorage._lock:
            MemoryStorage._data[key] = bytes(data)

    def read_bytes(self, key: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        with MemoryStorage._lock:
            try:
                blob = MemoryStorage._data[key]
            except KeyError:
                raise FileNotFoundError(f"mock://{key}") from None
        end = None if length is None else offset + length
        return blob[offset:end]

    def delete(self, key: str, missing_ok: bool = True) -> bool:
        with MemoryStorage._lock:
            if key in MemoryStorage._data:
                del MemoryStorage._data[key]
                return True
        if not missing_ok:
            raise FileNotFoundError(f"mock://{key}")
        return False

    def exists(self, key: str) -> bool:
        with MemoryStorage._lock:
            return key in MemoryStorage._data

    def list_prefix(self, key: str) -> List[str]:
        pre = key.rstrip("/") + "/"
        with MemoryStorage._lock:
            return sorted(k[len(pre):] for k in MemoryStorage._data
                          if k.startswith(pre))

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._data.clear()


class FlakyStorage(Storage):
    """Deterministic fault-injection wrapper (reference
    UnstableFileStorage / SlowFileStorage, external_storage.py:587/608).

    ``failure_rate`` fails exactly that fraction of writes (error-diffusion
    accumulator, no RNG: reproducible under pytest). ``slow_ms`` sleeps on
    every operation."""

    def __init__(self, inner: Storage, failure_rate: float = 0.0,
                 slow_ms: float = 0.0, fail_reads: bool = False):
        self.inner = inner
        self.failure_rate = failure_rate
        self.slow_ms = slow_ms
        self.fail_reads = fail_reads
        self._ops = 0
        self._fails = 0
        self._lock = threading.Lock()

    def _maybe_fail(self, what: str) -> None:
        if self.slow_ms:
            time.sleep(self.slow_ms / 1000.0)
        with self._lock:
            self._ops += 1
            if self._ops * self.failure_rate - self._fails >= 1.0:
                self._fails += 1
                raise OSError(f"injected storage fault ({what})")

    def write_bytes(self, key, data):
        self._maybe_fail("write")
        return self.inner.write_bytes(key, data)

    def read_bytes(self, key, offset=0, length=None):
        if self.fail_reads:
            self._maybe_fail("read")
        elif self.slow_ms:
            time.sleep(self.slow_ms / 1000.0)
        return self.inner.read_bytes(key, offset, length)

    def delete(self, key, missing_ok=True):
        return self.inner.delete(key, missing_ok)

    def exists(self, key):
        return self.inner.exists(key)

    def list_prefix(self, key):
        return self.inner.list_prefix(key)


class _GcsStorage(Storage):
    """gs:// behind an optional import; the hermetic TPU image has no
    cloud SDK, so on most boxes construction raises a clear error. With
    the SDK present, keys are ``bucket/path`` and ops map to blob
    upload/download; SDK errors surface as OSError so consumers' retry
    paths (spill scan, syncer) treat them as transient IO."""

    def __init__(self):
        try:
            from google.cloud import storage as gcs
        except ImportError:
            raise ImportError(
                "gs:// URIs need the google-cloud-storage package, which "
                "is not in this image; use file:// or mock://, or install "
                "it in your own environment") from None
        self._client = gcs.Client()

    def _blob(self, key: str):
        bucket, _, path = key.partition("/")
        return self._client.bucket(bucket).blob(path)

    def write_bytes(self, key: str, data) -> None:
        try:
            self._blob(key).upload_from_string(bytes(data))
        except Exception as e:
            raise OSError(f"gs write failed for {key}: {e}") from e

    def read_bytes(self, key: str, offset: int = 0,
                   length: Optional[int] = None) -> bytes:
        end = None if length is None else offset + length - 1
        try:
            return self._blob(key).download_as_bytes(
                start=offset or None, end=end)
        except Exception as e:
            if getattr(e, "code", None) == 404:
                raise FileNotFoundError(f"gs://{key}") from None
            raise OSError(f"gs read failed for {key}: {e}") from e

    def delete(self, key: str, missing_ok: bool = True) -> bool:
        try:
            self._blob(key).delete()
            return True
        except Exception as e:
            if getattr(e, "code", None) == 404:
                if not missing_ok:
                    raise FileNotFoundError(f"gs://{key}") from None
                return False
            raise OSError(f"gs delete failed for {key}: {e}") from e

    def exists(self, key: str) -> bool:
        try:
            return self._blob(key).exists()
        except Exception as e:
            raise OSError(f"gs exists failed for {key}: {e}") from e

    def list_prefix(self, key: str) -> List[str]:
        bucket, _, path = key.partition("/")
        pre = path.rstrip("/") + "/"
        try:
            blobs = self._client.list_blobs(bucket, prefix=pre)
            return sorted(b.name[len(pre):] for b in blobs)
        except Exception as e:
            raise OSError(f"gs list failed for {key}: {e}") from e


_REGISTRY: Dict[str, Storage] = {}
_REGISTRY_LOCK = threading.Lock()
_FACTORIES = {
    "file": FileStorage,
    "local": FileStorage,
    "mock": MemoryStorage,
    "memory": MemoryStorage,
    "gs": _GcsStorage,
}


def register_storage(scheme: str, storage: Storage) -> None:
    """Install a backend (or a wrapped one, e.g. FlakyStorage) for a
    scheme; tests use this to inject faults under real consumers."""
    with _REGISTRY_LOCK:
        _REGISTRY[scheme] = storage


def get_storage(uri: str) -> Tuple[Storage, str]:
    scheme, key = parse_uri(uri)
    with _REGISTRY_LOCK:
        st = _REGISTRY.get(scheme)
        if st is None:
            factory = _FACTORIES.get(scheme)
            if factory is None:
                raise ValueError(
                    f"unsupported storage scheme {scheme!r} in {uri!r} "
                    f"(known: {sorted(_FACTORIES)})")
            st = _REGISTRY[scheme] = factory()
    return st, key


# ----------------------------------------------------------- URI helpers
def read_bytes(uri: str, offset: int = 0,
               length: Optional[int] = None) -> bytes:
    st, key = get_storage(uri)
    return st.read_bytes(key, offset, length)


def write_bytes(uri: str, data: bytes) -> None:
    st, key = get_storage(uri)
    st.write_bytes(key, data)


def delete_uri(uri: str, missing_ok: bool = True) -> bool:
    st, key = get_storage(uri)
    return st.delete(key, missing_ok)


def exists(uri: str) -> bool:
    st, key = get_storage(uri)
    return st.exists(key)


def list_prefix(uri: str) -> List[str]:
    st, key = get_storage(uri)
    return st.list_prefix(key)


def open_reader(uri: str) -> io.BytesIO:
    """Whole-object reader for pandas/pyarrow-style consumers."""
    return io.BytesIO(read_bytes(uri))


def upload_dir(local_dir: str, uri: str) -> int:
    """Mirror a local directory tree under a URI prefix; returns file
    count (reference upload_to_uri, remote_storage.py:195)."""
    st, key = get_storage(uri)
    n = 0
    for dirpath, _dirs, files in os.walk(local_dir):
        for fn in files:
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, local_dir)
            with open(full, "rb") as f:
                st.write_bytes(join_uri(key, *rel.split(os.sep)), f.read())
            n += 1
    return n


def download_dir(uri: str, local_dir: str) -> int:
    """Materialize a URI prefix as a local directory tree (reference
    download_from_uri, remote_storage.py:177)."""
    st, key = get_storage(uri)
    os.makedirs(local_dir, exist_ok=True)
    n = 0
    for rel in st.list_prefix(key):
        dest = os.path.join(local_dir, *rel.split("/"))
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "wb") as f:
            f.write(st.read_bytes(join_uri(key, *rel.split("/"))))
        n += 1
    return n
