"""Control-plane RPC: length-prefixed pickled frames over TCP.

Plays the role of the reference's gRPC layer (/root/reference/src/ray/rpc/
grpc_server.h:73, client_call.h) for the Python control daemons.  Design goals
match the reference's: full-duplex connections (either side can push), request
multiplexing over one socket, per-connection ordering (the property the actor
task queue relies on), and reconnect-free failure surfacing (a dropped
connection fails all in-flight calls with ``ConnectionError``).

The data plane (large objects) never travels here — it goes through the
shared-memory store / chunked transfer, mirroring the reference's strict
control/data plane split (SURVEY.md §1).

Fast path (docs/rpc_fastpath.md):

* **Pooled dispatch** — requests run on a shared bounded thread pool
  (``rpc_dispatch_threads``) instead of a freshly spawned thread per RPC;
  on a 1-core box the per-request ``Thread.start()`` dominated small-RPC
  cost.  Per-connection dispatch order is unchanged (one reader enqueues
  in arrival order and the pool queue is FIFO).
* **Fast-method registry** — a server may mark handlers that never block
  and never call back into the connection (heartbeats, kv_get, liveness
  probes); those run inline on the reader thread, skipping the pool hop
  entirely.  Under schedule fuzz (``rpc_fuzz_ms``>0) fast methods fall
  back to the pool so the fuzzer can still perturb their interleaving.
* **Frame coalescing + scatter/gather writes** — frames are encoded to an
  iovec (header / buffer-length table / pickle body / out-of-band
  protocol-5 buffers) and written with ``sendmsg``; no length-prefix
  concatenation copy.  Writers enqueue frames and the first writer in
  drains the whole queue in one syscall batch, so back-to-back frames
  (pipelined pushes, batched replies) coalesce into one send.
* **recv_into framing** — the reader receives headers and pickle bodies
  into one reusable growable buffer instead of recv()+join allocations;
  out-of-band buffers land in fresh buffers (objects may keep views).
* **Stable frames** (docs/object_transfer.md) — a sender that guarantees
  a frame's out-of-band buffers stay immutable until written (sealed shm
  slices) passes ``stable=True`` + an ``on_sent`` hook: the write queue
  skips the defensive copy queued frames normally pay and fires the hook
  exactly once when the frame drains (or is dropped on failure), so the
  raylet's chunk server holds its shm pin only for the write's lifetime.

The inline-handler contract (machine-enforced)
----------------------------------------------

A handler registered via ``fast_methods`` runs ON THE CONNECTION'S
READER THREAD.  While it runs, nothing else is read off that socket —
so the contract is strict:

* it may **buffer, mutate in-memory state under short locks, notify
  waiters, enqueue reply/push frames, and return a value or a
  ``Deferred``** (resolved later from another thread);
* it must **never wait on another thread's or the peer's progress**: no
  ``time.sleep``, no ``Future.result`` / ``Event.wait`` /
  ``Condition.wait``, no synchronous ``Connection.call`` (its response
  arrives on a reader — possibly THIS one), no ``ray_tpu.get`` / store
  fetches, no socket receives, no pool submits that are awaited.

The failure shape is not a slowdown but a distributed deadlock: on a
full-duplex connection, an inline handler blocking on reply drain stops
the reader whose peer may be blocked symmetrically on us (observed in
the collective take-handler incident, util/collective/transport.py).
Enqueueing frames is fine — ``_send`` may opportunistically flush, but
that is the transport's own bounded tradeoff, not a wait on a peer.

Handlers that are fast only CONDITIONALLY (worker_main's ``push_tasks``
is inline only for ref-free frames) must encode the condition in the
registration predicate, so the slow variant takes the pooled path.

This contract is enforced by the ``inline-handler-purity`` raylint
checker (docs/static_analysis.md): every registered fast name is
resolved to its handler and the call graph walked for blocking
primitives; violations fail tier-1.  Justified exceptions carry an
inline ``# raylint: disable=...`` comment with the reason.
"""

from __future__ import annotations

import itertools
import os
import pickle
import queue
import random
import socket
import struct
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from ray_tpu._private.config import CONFIG
from ray_tpu._private.logging_utils import get_logger
from ray_tpu._private import runtime_metrics as rtm

logger = get_logger("rpc")

# hot-path instruments, bound once at import (docs/observability.md):
# record calls are attribute arithmetic, no-ops when telemetry is off.
# _TELEMETRY guards the few sites whose *argument* computation isn't
# free (byte sums), so the kill switch removes that cost too.
_TELEMETRY = rtm.enabled()
_M_DISPATCH = rtm.histogram_family(
    "ray_tpu_rpc_dispatch_ms", "per-method RPC handler latency (ms)")
_M_INLINE = rtm.counter(
    "ray_tpu_rpc_dispatch_inline_total",
    "requests run inline on the reader thread (fast-method registry)")
_M_POOLED = rtm.counter(
    "ray_tpu_rpc_dispatch_pooled_total",
    "requests dispatched through the shared thread pool")
_M_FRAMES_OUT = rtm.counter(
    "ray_tpu_rpc_frames_sent_total", "frames written to the wire")
_M_SEND_BATCHES = rtm.counter(
    "ray_tpu_rpc_send_batches_total",
    "sendmsg flush batches (frames/batches = write coalescing factor)")
_M_BYTES_OUT = rtm.counter(
    "ray_tpu_rpc_bytes_sent_total", "payload bytes written to the wire")
_M_BYTES_IN = rtm.counter(
    "ray_tpu_rpc_bytes_received_total", "payload bytes read off the wire")
_M_WQ_DEPTH = rtm.gauge(
    "ray_tpu_rpc_write_queue_depth",
    "high-water write-queue depth since the last flush", watermark=True)

# cached (generation, value) of CONFIG.rpc_fuzz_ms: the old per-dispatch
# `from ...config import CONFIG` + flag resolution (lock + env lookup +
# parse) was measurable on the RPC hot path.  CONFIG.generation() bumps
# on every set()/update(), so runtime overrides (ray_tpu.init's
# system_config) still take effect; raw os.environ writes made after the
# first dispatch are not observed (set the flag via CONFIG instead).
_fuzz_gen = -1
_fuzz_ms = 0.0


def _fuzz_ms_now() -> float:
    global _fuzz_gen, _fuzz_ms
    gen = CONFIG.generation()
    if gen != _fuzz_gen:
        _fuzz_ms = CONFIG.rpc_fuzz_ms
        _fuzz_gen = gen
    return _fuzz_ms


def _maybe_fuzz() -> None:
    """Schedule fuzzing: jitter every RPC dispatch by up to
    ``rpc_fuzz_ms`` (config; default 0 = off).

    The single-language analog of the reference's TSAN/schedule-stress
    race tooling: a handler that only works because replies "usually
    arrive in order" fails under fuzz.  The race-sensitive suites
    (lease races, chaos, GCS fault tolerance) run under it in
    tests/test_sched_fuzz.py."""
    ms = _fuzz_ms_now()
    if ms > 0:
        time.sleep(random.uniform(0.0, ms / 1000.0))


# distributed-tracing context install around dispatch (lazy import: rpc
# is imported by everything, tracing_helper only needs CONFIG but the
# indirection keeps cold rpc startup free of it)
_trace_mod = None


def _trace_install(ctx):
    global _trace_mod
    if _trace_mod is None:
        from ray_tpu.util.tracing import tracing_helper
        _trace_mod = tracing_helper
    return _trace_mod.install(ctx)


def _trace_uninstall(token) -> None:
    _trace_mod.uninstall(token)


# wire format: one frame is
#   <IIBQ>  (pickle_len, nbufs, kind, msg_id)
#   nbufs * <Q>  out-of-band buffer lengths
#   pickle body (protocol 5)
#   out-of-band buffers, concatenated
# kind/msg_id ride the fixed header (duplicating the pickled tuple) so
# the reader can route a response's out-of-band buffers to a registered
# buffer sink BEFORE unpickling — the bulk-data pull path receives chunk
# payloads straight into their shm destination offsets with recv_into
# (docs/object_transfer.md), no per-chunk allocation or copy.
# All peers are in-repo daemons spawned from the same tree (csrc/rpcnet.h
# is the C++ twin), so the format needs no version negotiation.
_HDR = struct.Struct("<IIBQ")
_BLEN = struct.Struct("<Q")
_REQUEST, _RESPONSE, _PUSH = 0, 1, 2

# sendmsg iovec batching cap: well under any platform IOV_MAX, large
# enough that a burst of small frames still coalesces into few syscalls
_IOV_BATCH = 64
# write-queue soft cap: beyond this, enqueuers block until the active
# flusher drains (backpressure instead of unbounded buffering)
_WQ_CAP = 1024
# out-of-band buffer count cap, enforced on BOTH sides: the sender falls
# back to in-band pickling past it, the receiver treats a bigger count
# as a garbled header (protocol-mismatch guard)
_NBUFS_MAX = 4096
# frame payload ceiling, enforced on BOTH sides: the sender fails the
# one oversized call with ValueError; the receiver treats a bigger
# header as garbled and drops the connection.  Control-plane payloads
# this large are a bug — bulk data belongs to the store/chunk transfer.
_BODY_MAX = 1 << 30


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler raised on the remote side; wraps the original exception."""

    def __init__(self, cause: BaseException):
        super().__init__(repr(cause))
        self.cause = cause


class Deferred:
    """Out-of-band reply handle: a handler returns one of these and some
    other thread resolves it later, sending the response directly from
    the resolving thread.

    This removes the parked-thread pattern (handler blocks on an Event a
    worker loop sets, then wakes just to return) — on a contended box
    that wake-to-reply hop is a full context switch per RPC.  Resolution
    and binding race safely: whichever happens second sends the reply.

    ``resolve(value, stable=True, on_sent=cb)`` marks the reply frame's
    out-of-band buffers as immutable-until-sent: the write queue ships
    them zero-copy (no defensive materialization) and invokes ``cb``
    exactly once after the frame drains to the socket — or is dropped by
    a connection failure.  The raylet's chunk server uses this to pin a
    shm slice only for the lifetime of the write
    (docs/object_transfer.md)."""

    _UNSET = object()
    __slots__ = ("_lock", "_conn", "_msg_id", "_result")

    def __init__(self):
        self._lock = threading.Lock()
        self._conn: Optional["Connection"] = None
        self._msg_id: Optional[int] = None
        self._result = Deferred._UNSET

    def _bind(self, conn: "Connection", msg_id: int) -> None:
        with self._lock:
            self._conn, self._msg_id = conn, msg_id
            result = self._result
        if result is not Deferred._UNSET:
            ok, value, stable, on_sent = result
            conn._respond(msg_id, ok, value, stable=stable,
                          on_sent=on_sent)

    def resolve(self, value: Any, *, stable: bool = False,
                on_sent: Optional[Callable[[], None]] = None) -> None:
        self._finish(True, value, stable, on_sent)

    def fail(self, error: BaseException) -> None:
        self._finish(False, error, False, None)

    def _finish(self, ok: bool, value: Any, stable: bool,
                on_sent: Optional[Callable[[], None]]) -> None:
        with self._lock:
            if self._result is not Deferred._UNSET:
                if on_sent is not None:
                    _run_cb(on_sent)  # double-resolve must not leak pins
                return
            self._result = (ok, value, stable, on_sent)
            conn, msg_id = self._conn, self._msg_id
        if conn is not None:
            conn._respond(msg_id, ok, value, stable=stable, on_sent=on_sent)


def _run_cb(cb: Optional[Callable[[], None]]) -> None:
    if cb is None:
        return
    try:
        cb()
    except Exception:
        logger.exception("rpc on_sent callback failed")


# ---------------------------------------------------------------- dispatch
_pool_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_pid = 0


def _dispatch_pool() -> ThreadPoolExecutor:
    """Process-wide bounded executor for RPC request handlers.

    Replaces thread-per-request: idle workers are reused, new ones spawn
    only when all are busy (up to ``rpc_dispatch_threads``).  Per-
    connection request order is preserved — the pool queue is FIFO and
    each connection's reader enqueues in arrival order.  A request can
    only wait on strictly-earlier traffic of its own connection (actor
    seqs, dependency-ordered pushes), which is already dispatched ahead
    of it, so the bound introduces no new deadlocks.

    Fork guard: a forked child (zygote workers) inherits the executor
    object but none of its threads — submitting into it would hang, so
    the pool is re-created when the pid changes."""
    global _pool, _pool_pid
    pid = os.getpid()
    if _pool is None or _pool_pid != pid:
        with _pool_lock:
            if _pool is None or _pool_pid != pid:
                _pool = ThreadPoolExecutor(
                    max_workers=max(1, CONFIG.rpc_dispatch_threads),
                    thread_name_prefix="rpc-dispatch")
                _pool_pid = pid
    return _pool


# ---------------------------------------------------------------- framing
def _encode_frame(obj: Any) -> list:
    """Pickle the (kind, msg_id, a, b) tuple ``obj`` into an iovec
    [header, lentable?, body, *buffers].

    Protocol-5 ``buffer_callback`` keeps large contiguous buffers (numpy
    arrays, PickleBuffer-wrapped blobs) out of the pickle stream: they
    ride the iovec zero-copy and ``sendmsg`` gathers them on the wire.
    Non-contiguous buffers fall back to in-band pickling."""
    pbufs: list = []
    try:
        body = pickle.dumps(obj, protocol=5, buffer_callback=pbufs.append)
        if len(pbufs) > _NBUFS_MAX:
            # a payload of thousands of small arrays: past the receiver's
            # header sanity cap, so carry it in band instead
            raise ValueError("too many out-of-band buffers")
        raws = [pb.raw() for pb in pbufs]
    except (BufferError, ValueError):
        body = pickle.dumps(obj, protocol=5)
        raws = []
    if len(body) > _BODY_MAX or sum(len(r) for r in raws) > _BODY_MAX:
        # fail THIS call (call_async surfaces it on the future) instead
        # of letting the receiver's header guard drop the connection
        raise ValueError(
            f"rpc frame exceeds {_BODY_MAX} bytes; move bulk data "
            f"through the object store")
    iov = [_HDR.pack(len(body), len(raws), obj[0], obj[1] or 0)]
    if raws:
        iov.append(b"".join(_BLEN.pack(len(r)) for r in raws))
    iov.append(body)
    iov.extend(raws)
    return iov


def _sendmsg_all(sock: socket.socket, bufs: list) -> None:
    """Scatter/gather send of the whole iovec, handling partial writes."""
    pending = deque(memoryview(b).cast("B") if not isinstance(b, bytes)
                    else memoryview(b) for b in bufs if len(b))
    while pending:
        sent = sock.sendmsg(list(itertools.islice(pending, _IOV_BATCH)))
        while sent:
            head = pending[0]
            if len(head) <= sent:
                sent -= len(head)
                pending.popleft()
            else:
                pending[0] = head[sent:]
                sent = 0


def _recv_exact_into(sock: socket.socket, buf: memoryview, n: int) -> None:
    got = 0
    while got < n:
        r = sock.recv_into(buf[got:n], n - got)
        if not r:
            raise ConnectionError("socket closed")
        got += r


def _grow(scratch: bytearray, n: int) -> bytearray:
    if len(scratch) < n:
        scratch = bytearray(max(n, 2 * len(scratch)))
    return scratch


def _normalize_fast(fast_methods):
    """None, a predicate f(method, payload) -> bool, or a name set."""
    if fast_methods is None or callable(fast_methods):
        return fast_methods
    return frozenset(fast_methods)


class Connection:
    """One duplex connection; used by both client and server sides."""

    def __init__(self, sock: socket.socket,
                 handler: Optional[Callable[["Connection", str, Any], Any]] = None,
                 push_handler: Optional[Callable[[str, Any], None]] = None,
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 fast_methods: Optional[Iterable[str]] = None):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._handler = handler
        self._push_handler = push_handler
        self._on_close = on_close
        # handlers that never block and never call back into this
        # connection run inline on the reader thread (no pool hop): a set
        # of method names, or a predicate ``f(method, payload) -> bool``
        # for payload-dependent decisions (e.g. ref-free task batches)
        self._fast_methods = _normalize_fast(fast_methods)
        self._ids = itertools.count(1)
        self._inflight: Dict[int, Future] = {}
        self._inflight_lock = threading.Lock()
        # buffer sinks (docs/object_transfer.md): msg_id -> f(lens) that
        # may hand the reader destination memoryviews for a response's
        # out-of-band buffers (recv_into shm, zero-copy).  _sink_active
        # marks the one the reader is currently receiving into so
        # discard_sinks can wait it out before its memory is released.
        self._sinks: Dict[int, Callable] = {}
        self._sink_lock = threading.Lock()
        self._sink_cv = threading.Condition(self._sink_lock)
        self._sink_active: Optional[int] = None
        self._closed = threading.Event()
        # write-side frame queue: the first writer in becomes the flusher
        # and drains everything queued behind it in coalesced sendmsg
        # batches; later writers enqueue and return (or block at _WQ_CAP)
        self._wq: deque = deque()
        self._wq_lock = threading.Lock()
        self._wq_cv = threading.Condition(self._wq_lock)
        self._flushing = False
        self._push_queue = None   # created lazily on first push
        self.peer: Any = None  # attachable identity (e.g. worker id)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # ------------------------------------------------------------------ send
    def _send(self, obj: Any, stable: bool = False,
              on_sent: Optional[Callable[[], None]] = None) -> None:
        """Enqueue one frame and flush opportunistically.

        If another thread is mid-flush it picks our frame up before it
        releases the socket, so back-to-back frames from concurrent
        writers coalesce into one ``sendmsg``.  Send failures close the
        connection; writers whose frames were queued behind a failed
        flush observe it through their futures (close() fails them).

        ``stable=True`` promises the frame's buffers stay immutable until
        ``on_sent`` fires, so a queued frame keeps its zero-copy views
        instead of being defensively materialized — the bulk-data path's
        contract (shm slices of sealed objects).  ``on_sent`` runs
        exactly once: after the frame hits the socket, or when it is
        dropped by a failed flush / close()."""
        try:
            iov = _encode_frame(obj)  # may raise (unpicklable payload)
        except BaseException:
            _run_cb(on_sent)  # frame never enqueued: release pins here
            raise
        with self._wq_lock:
            while (len(self._wq) >= _WQ_CAP and self._flushing
                   and not self._closed.is_set()):
                self._wq_cv.wait(1.0)
            if self._closed.is_set():
                _run_cb(on_sent)
                raise ConnectionError("connection closed")
            self._wq.append((iov, on_sent))
            _M_WQ_DEPTH.set_max(len(self._wq))
            if self._flushing:
                if not stable:
                    # the active flusher sends this frame after we return;
                    # materialize zero-copy views — the caller may mutate
                    # the backing buffer once its call returns
                    iov[:] = [b if isinstance(b, bytes) else bytes(b)
                              for b in iov]
                return
            self._flushing = True
        self._flush()

    def _drain_wq_locked(self) -> list:
        """_wq_lock held: clear the queue, returning its on_sent hooks."""
        cbs = [cb for _iov, cb in self._wq if cb is not None]
        self._wq.clear()
        self._wq_cv.notify_all()
        return cbs

    def _flush(self) -> None:
        while True:
            with self._wq_lock:
                if not self._wq or self._closed.is_set():
                    self._flushing = False
                    dropped = self._drain_wq_locked()
                    if self._closed.is_set():
                        for cb in dropped:
                            _run_cb(cb)
                        raise ConnectionError("connection closed")
                    return
                batch: list = []
                sent_cbs: list = []
                nframes = 0
                while self._wq and len(batch) < _IOV_BATCH:
                    iov, cb = self._wq.popleft()
                    batch.extend(iov)
                    if cb is not None:
                        sent_cbs.append(cb)
                    nframes += 1
                self._wq_cv.notify_all()
            if _TELEMETRY:
                _M_FRAMES_OUT.inc(nframes)
                _M_SEND_BATCHES.inc()
                _M_BYTES_OUT.inc(sum(len(b) for b in batch))
            try:
                _sendmsg_all(self._sock, batch)
            except BaseException:
                # a partial write leaves the stream desynced — the
                # connection is unusable regardless of the error type.
                # _flushing stays SET forever: a concurrent _send racing
                # the close() below must never become a new flusher and
                # splice a fresh header into the half-sent frame.  Its
                # frame lands in the queue unsent; close() fails its
                # future (pushes are fire-and-forget anyway) and wakes
                # cap-waiters.
                with self._wq_lock:
                    dropped = self._drain_wq_locked()
                for cb in itertools.chain(sent_cbs, dropped):
                    _run_cb(cb)
                self.close()
                raise
            for cb in sent_cbs:
                _run_cb(cb)

    def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        fut = self.call_async(method, payload)
        try:
            return fut.result(timeout)
        except (_FutureTimeout, TimeoutError):
            # Drop the abandoned future so a late response isn't delivered
            # to it and _inflight doesn't grow unbounded on timeouts.
            # NOTE: on 3.10 Future.result raises concurrent.futures
            # .TimeoutError, which is NOT the builtin TimeoutError (they
            # merged in 3.11) — catching only the builtin silently skipped
            # this reap and _inflight leaked one entry per timed-out call.
            msg_id = getattr(fut, "_rpc_msg_id", None)
            if msg_id is not None:
                with self._inflight_lock:
                    self._inflight.pop(msg_id, None)
                self.discard_sinks((msg_id,))
            raise

    def call_async(self, method: str, payload: Any = None,
                   buffer_sink: Optional[Callable] = None) -> Future:
        """``buffer_sink``: optional ``f(lens) -> list[memoryview] |
        None`` consulted when this call's response arrives carrying
        out-of-band buffers — returning destination views makes the
        reader ``recv_into`` them directly (the pull engine passes shm
        offsets).  The caller promises the views stay writable until the
        future resolves or ``discard_sinks`` returns."""
        fut: Future = Future()
        msg_id = next(self._ids)
        fut._rpc_msg_id = msg_id  # used by call() to reap timed-out futures
        with self._inflight_lock:
            if self._closed.is_set():
                fut.set_exception(ConnectionError("connection closed"))
                return fut
            self._inflight[msg_id] = fut
        if buffer_sink is not None:
            with self._sink_lock:
                self._sinks[msg_id] = buffer_sink
        try:
            self._send((_REQUEST, msg_id, method, payload))
        except OSError as e:
            self._reap_failed_send(msg_id)
            if not fut.done():  # close() may have failed it concurrently
                fut.set_exception(ConnectionError(str(e)))
        except Exception as e:  # e.g. unpicklable payload
            self._reap_failed_send(msg_id)
            if not fut.done():
                fut.set_exception(e)
        return fut

    def _reap_failed_send(self, msg_id: int) -> None:
        with self._inflight_lock:
            self._inflight.pop(msg_id, None)
        with self._sink_lock:
            self._sinks.pop(msg_id, None)

    def abandon(self, msg_ids: Iterable[int],
                timeout: float = 2.0) -> None:
        """Give up on outstanding ``call_async`` futures: drop their
        ``_inflight`` entries (a late response is discarded instead of
        delivered, and the map can't grow unbounded on a pooled
        connection to a wedged-but-alive peer) and withdraw their buffer
        sinks (``discard_sinks``)."""
        ids = list(msg_ids)
        with self._inflight_lock:
            for m in ids:
                self._inflight.pop(m, None)
        self.discard_sinks(ids, timeout)

    def discard_sinks(self, msg_ids: Iterable[int],
                      timeout: float = 2.0) -> None:
        """Withdraw buffer sinks: after this returns the reader will
        never again touch their destination views, so the caller may
        release the backing memory (abort a partially pulled shm
        create).  If the reader is mid-``recv_into`` one of them and
        doesn't finish within ``timeout`` (peer wedged mid-frame), the
        connection is closed — the shutdown unblocks the recv."""
        ids = set(msg_ids)
        if not ids:
            return
        with self._sink_lock:
            for m in ids:
                self._sinks.pop(m, None)
            deadline = time.monotonic() + timeout
            while self._sink_active in ids \
                    and time.monotonic() < deadline:
                self._sink_cv.wait(max(0.01,
                                       deadline - time.monotonic()))
        if self._sink_active in ids:
            self.close()  # unblocks the wedged recv; reader clears active
            with self._sink_lock:
                while self._sink_active in ids:
                    self._sink_cv.wait(1.0)

    def push(self, method: str, payload: Any = None) -> None:
        """Fire-and-forget message (pubsub notifications, log batches).

        A dead socket closes the connection (so later pushes fail fast
        and on_close/pubsub cleanup runs) and raises ConnectionError."""
        try:
            self._send((_PUSH, 0, method, payload))
        except OSError as e:
            self.close()
            raise ConnectionError(str(e)) from e

    # ------------------------------------------------------------------ recv
    def _take_sink(self, msg_id: int, lens: list) -> Optional[list]:
        """Reader side: destination views for this response's buffers if
        a sink is registered and accepts them; marks the sink active so
        discard_sinks won't let the memory go while we recv into it."""
        with self._sink_lock:
            sink = self._sinks.pop(msg_id, None)
            if sink is None:
                return None
            try:
                dests = sink(lens)
            except Exception:
                logger.exception("buffer sink failed")
                dests = None
            if dests is not None and (
                    len(dests) != len(lens)
                    or any(len(d) != n for d, n in zip(dests, lens))):
                # a miscounted/missized sink would desync the frame
                # stream and tear down the shared connection — fall back
                # to fresh storage instead of trusting it
                logger.error("buffer sink returned wrong shapes for "
                             "msg %d; ignoring it", msg_id)
                dests = None
            if dests is not None:
                self._sink_active = msg_id
            return dests

    def _sink_done(self) -> None:
        if self._sink_active is not None:
            with self._sink_lock:
                self._sink_active = None
                self._sink_cv.notify_all()

    def _read_loop(self) -> None:
        sock = self._sock
        scratch = bytearray(64 * 1024)
        try:
            while True:
                view = memoryview(scratch)
                _recv_exact_into(sock, view, _HDR.size)
                body_len, nbufs, kind, msg_id = _HDR.unpack_from(view)
                if body_len > _BODY_MAX or nbufs > _NBUFS_MAX:
                    # garbled header (e.g. a peer speaking an older frame
                    # layout): fail the connection instead of blocking on
                    # a bogus multi-GB read
                    raise ConnectionError("garbled rpc frame header")
                bufs = None
                lens = ()
                if nbufs:
                    lens_sz = _BLEN.size * nbufs
                    scratch = _grow(scratch, lens_sz)
                    view = memoryview(scratch)
                    _recv_exact_into(sock, view, lens_sz)
                    lens = [_BLEN.unpack_from(view, i * _BLEN.size)[0]
                            for i in range(nbufs)]
                    if sum(lens) > _BODY_MAX:
                        # same sanity bound as the header: a corrupt u64
                        # must not zero-fill a giant allocation
                        raise ConnectionError("garbled rpc buffer table")
                if len(scratch) < body_len:
                    scratch = _grow(scratch, body_len)
                view = memoryview(scratch)
                _recv_exact_into(sock, view, body_len)
                if nbufs:
                    # a registered buffer sink (pull engine) receives the
                    # payload straight into its shm destination offsets;
                    # otherwise out-of-band buffers get fresh storage —
                    # deserialized objects (numpy views) may keep
                    # references into them
                    bufs = self._take_sink(msg_id, lens) \
                        if kind == _RESPONSE else None
                    if bufs is not None:
                        for ln, dest in zip(lens, bufs):
                            _recv_exact_into(sock, dest, ln)
                    else:
                        bufs = []
                        for ln in lens:
                            b = bytearray(ln)
                            _recv_exact_into(sock, memoryview(b), ln)
                            bufs.append(b)
                kind, msg_id, a, b = pickle.loads(view[:body_len],
                                                  buffers=bufs)
                # the buffers are fully received and wrapped: a pending
                # discard_sinks may release their memory from here on
                self._sink_done()
                if _TELEMETRY:
                    _M_BYTES_IN.inc(_HDR.size + body_len +
                                    ((_BLEN.size * nbufs + sum(lens))
                                     if nbufs else 0))
                if kind == _REQUEST:
                    fm = self._fast_methods
                    if (fm is not None and _fuzz_ms_now() == 0
                            and (fm(a, b) if callable(fm) else a in fm)):
                        # registered non-blocking handler: run inline on
                        # the reader (the reply coalesces with whatever
                        # the previous frame left in the write queue)
                        _M_INLINE.inc()
                        self._handle_request(msg_id, a, b)
                    else:
                        _M_POOLED.inc()
                        _dispatch_pool().submit(
                            self._handle_request, msg_id, a, b)
                elif kind == _RESPONSE:
                    if self._sinks:
                        # an in-band reply (absent / spilled data) never
                        # consults its sink: drop the registration here
                        with self._sink_lock:
                            self._sinks.pop(msg_id, None)
                    with self._inflight_lock:
                        fut = self._inflight.pop(msg_id, None)
                    if fut is not None:
                        ok, value = a, b
                        if ok:
                            fut.set_result(value)
                        else:
                            fut.set_exception(RemoteError(value))
                else:  # _PUSH
                    if self._push_handler is not None:
                        # off the read loop: a push handler that makes a
                        # synchronous call on THIS connection would otherwise
                        # deadlock (its response can never be read). A serial
                        # queue keeps per-connection push ordering (pubsub
                        # state transitions rely on it).
                        self._enqueue_push(a, b)
        except (ConnectionError, OSError, EOFError, RuntimeError,
                pickle.UnpicklingError):
            # RuntimeError: dispatch pool shut down at interpreter exit
            pass
        finally:
            self._sink_done()  # a discard_sinks waiter must not hang
            self.close()

    def _enqueue_push(self, method: str, payload: Any) -> None:
        if self._push_queue is None:
            self._push_queue = queue.Queue()
            threading.Thread(target=self._push_loop, daemon=True).start()
        self._push_queue.put((method, payload))

    def _push_loop(self) -> None:
        # keeps draining after close(): pushes already received rode the
        # stream intact before the EOF, and droppers would lose delivered
        # results (core_worker's task_done stream relies on this — see
        # drain_pushes)
        while True:
            try:
                method, payload = self._push_queue.get(timeout=0.2)
            except queue.Empty:
                if self._closed.is_set():
                    return  # closed AND backlog drained
                continue
            try:
                self._push_handler(method, payload)
            except Exception:
                logger.exception("push handler failed for %s", method)
            finally:
                self._push_queue.task_done()

    def drain_pushes(self, timeout: float = 5.0) -> None:
        """Block until every push received before the connection closed
        has been handed to the push handler.  Callers that reconcile
        state after a connection death (e.g. requeueing work the peer
        may have finished) must drain first or they race the serial push
        thread's backlog."""
        q = self._push_queue
        if q is None:
            return
        deadline = time.monotonic() + timeout
        while q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    def _handle_request(self, msg_id: int, method: str, payload: Any) -> None:
        t0 = rtm.now()
        trace_token = None
        try:
            if self._handler is None:
                raise RpcError(f"no handler for {method}")
            _maybe_fuzz()
            if type(payload) is dict and "_trace_ctx" in payload:
                # distributed-tracing propagation (docs/observability.md):
                # a caller that stamped its trace context onto the payload
                # (streaming item reports, transfer-plane chunk fetches)
                # has the handler run inside that trace — spans it opens
                # join the request's trace with no per-method plumbing.
                # Popped before the handler, restored after: dispatch-pool
                # threads are reused and must not leak a context.
                trace_token = _trace_install(payload.pop("_trace_ctx"))
            result = self._handler(self, method, payload)
            if isinstance(result, Deferred):
                # the reply is sent by whichever thread resolves it;
                # latency here covers the synchronous handler part only
                result._bind(self, msg_id)
                _M_DISPATCH.observe_since(method, t0)
                return
            ok, value = True, result
        except BaseException as e:  # noqa: BLE001 - errors cross the wire
            ok, value = False, e
        finally:
            if trace_token is not None:
                _trace_uninstall(trace_token)
        _M_DISPATCH.observe_since(method, t0)
        self._respond(msg_id, ok, value)

    def _respond(self, msg_id: int, ok: bool, value: Any,
                 stable: bool = False,
                 on_sent: Optional[Callable[[], None]] = None) -> None:
        # _send owns on_sent once called: it fires the hook itself on
        # every failure path, so no branch here may re-run it
        try:
            self._send((_RESPONSE, msg_id, ok, value), stable=stable,
                       on_sent=on_sent)
        except OSError:
            self.close()
        except Exception as e:
            # Result/exception wasn't picklable — still answer the caller so
            # its call() never hangs.
            try:
                self._send((_RESPONSE, msg_id, False,
                            RpcError(f"unserializable reply: {e!r}")))
            except OSError:
                self.close()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with self._wq_lock:
            dropped = self._drain_wq_locked()
        for cb in dropped:
            _run_cb(cb)  # unsent stable frames must still release pins
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._inflight_lock:
            inflight, self._inflight = self._inflight, {}
        for fut in inflight.values():
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))
        with self._sink_lock:
            self._sinks.clear()  # registered-but-unserved destinations
        if self._on_close is not None:
            cb, self._on_close = self._on_close, None
            try:
                cb(self)
            except Exception:
                logger.exception("on_close callback failed")

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class Server:
    """Pooled RPC server.

    ``handler(conn, method, payload)`` runs on a shared bounded dispatch
    pool (or inline on the reader for registered ``fast_methods``); per-
    connection request *dispatch* order is preserved by the reader loop, and
    handlers that need strict ordering (actor queues) do their own sequencing.
    """

    def __init__(self, handler: Callable[[Connection, str, Any], Any],
                 host: str = "127.0.0.1", port: int = 0,
                 on_disconnect: Optional[Callable[[Connection], None]] = None,
                 fast_methods: Optional[Iterable[str]] = None):
        self._handler = handler
        self._on_disconnect = on_disconnect
        self._fast_methods = _normalize_fast(fast_methods)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._conns: set[Connection] = set()
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._stopped.is_set():
                    break
                # Transient failure (e.g. EMFILE): keep the server alive.
                logger.exception("accept() failed; retrying")
                time.sleep(0.1)
                continue
            conn = Connection(sock, handler=self._handler,
                              on_close=self._conn_closed,
                              fast_methods=self._fast_methods)
            self._register_conn(conn)

    def _register_conn(self, conn: Connection) -> bool:
        """Track a freshly accepted connection; closes it instead when
        stop() already ran.  An accept landing between stop()'s
        ``_stopped.set()`` and its ``connections()`` snapshot would
        otherwise never be closed — its live server-side reader then
        silently consumes the client's pushes forever, so the client
        never observes EOF and hangs instead of getting a
        ConnectionError."""
        with self._lock:
            if not self._stopped.is_set():
                self._conns.add(conn)
                return True
        conn.close()
        return False

    def _conn_closed(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)
        if self._on_disconnect is not None and not self._stopped.is_set():
            self._on_disconnect(conn)

    def connections(self) -> list[Connection]:
        with self._lock:
            return list(self._conns)

    def rebind(self, handler: Callable[[Connection, str, Any], Any],
               fast_methods=None) -> None:
        """Swap the dispatch handler (and fast registry) for this server
        AND its live connections — e.g. a worker extending its embedded
        core-worker server with task-execution methods after startup."""
        fast = _normalize_fast(fast_methods)
        self._handler = handler
        self._fast_methods = fast
        for conn in self.connections():
            conn._handler = handler
            conn._fast_methods = fast

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in self.connections():
            conn.close()


def connect(address: Tuple[str, int],
            push_handler: Optional[Callable[[str, Any], None]] = None,
            handler: Optional[Callable[[Connection, str, Any], Any]] = None,
            timeout: float = 30.0,
            on_close: Optional[Callable[[Connection], None]] = None,
            fast_methods: Optional[Iterable[str]] = None) -> Connection:
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    return Connection(sock, handler=handler, push_handler=push_handler,
                      on_close=on_close, fast_methods=fast_methods)
