"""Control-plane RPC: length-prefixed pickled frames over TCP.

Plays the role of the reference's gRPC layer (/root/reference/src/ray/rpc/
grpc_server.h:73, client_call.h) for the Python control daemons.  Design goals
match the reference's: full-duplex connections (either side can push), request
multiplexing over one socket, per-connection ordering (the property the actor
task queue relies on), and reconnect-free failure surfacing (a dropped
connection fails all in-flight calls with ``ConnectionError``).

The data plane (large objects) never travels here — it goes through the
shared-memory store / chunked transfer, mirroring the reference's strict
control/data plane split (SURVEY.md §1).
"""

from __future__ import annotations

import itertools
import pickle
import socket
import struct
import threading
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu._private.logging_utils import get_logger

logger = get_logger("rpc")


def _maybe_fuzz() -> None:
    """Schedule fuzzing: jitter every RPC dispatch by up to
    ``rpc_fuzz_ms`` (config; default 0 = off).

    The single-language analog of the reference's TSAN/schedule-stress
    race tooling: a handler that only works because replies "usually
    arrive in order" fails under fuzz.  The race-sensitive suites
    (lease races, chaos, GCS fault tolerance) run under it in
    tests/test_sched_fuzz.py."""
    from ray_tpu._private.config import CONFIG
    ms = CONFIG.rpc_fuzz_ms
    if ms > 0:
        import random
        import time as _time
        _time.sleep(random.uniform(0.0, ms / 1000.0))

_LEN = struct.Struct("<I")
_REQUEST, _RESPONSE, _PUSH = 0, 1, 2


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler raised on the remote side; wraps the original exception."""

    def __init__(self, cause: BaseException):
        super().__init__(repr(cause))
        self.cause = cause


def _send_frame(sock: socket.socket, lock: threading.Lock, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=5)
    with lock:
        sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("socket closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class Connection:
    """One duplex connection; used by both client and server sides."""

    def __init__(self, sock: socket.socket,
                 handler: Optional[Callable[["Connection", str, Any], Any]] = None,
                 push_handler: Optional[Callable[[str, Any], None]] = None,
                 on_close: Optional[Callable[["Connection"], None]] = None):
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        self._wlock = threading.Lock()
        self._handler = handler
        self._push_handler = push_handler
        self._on_close = on_close
        self._ids = itertools.count(1)
        self._inflight: Dict[int, Future] = {}
        self._inflight_lock = threading.Lock()
        self._closed = threading.Event()
        self._push_queue = None   # created lazily on first push
        self.peer: Any = None  # attachable identity (e.g. worker id)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # ------------------------------------------------------------------ send
    def call(self, method: str, payload: Any = None, timeout: float | None = None) -> Any:
        fut = self.call_async(method, payload)
        try:
            return fut.result(timeout)
        except TimeoutError:
            # Drop the abandoned future so a late response isn't delivered
            # to it and _inflight doesn't grow unbounded on timeouts.
            msg_id = getattr(fut, "_rpc_msg_id", None)
            if msg_id is not None:
                with self._inflight_lock:
                    self._inflight.pop(msg_id, None)
            raise

    def call_async(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()
        msg_id = next(self._ids)
        fut._rpc_msg_id = msg_id  # used by call() to reap timed-out futures
        with self._inflight_lock:
            if self._closed.is_set():
                fut.set_exception(ConnectionError("connection closed"))
                return fut
            self._inflight[msg_id] = fut
        try:
            _send_frame(self._sock, self._wlock, (_REQUEST, msg_id, method, payload))
        except OSError as e:
            with self._inflight_lock:
                self._inflight.pop(msg_id, None)
            if not fut.done():  # close() may have failed it concurrently
                fut.set_exception(ConnectionError(str(e)))
        except Exception as e:  # e.g. unpicklable payload
            with self._inflight_lock:
                self._inflight.pop(msg_id, None)
            if not fut.done():
                fut.set_exception(e)
        return fut

    def push(self, method: str, payload: Any = None) -> None:
        """Fire-and-forget message (pubsub notifications, log batches)."""
        try:
            _send_frame(self._sock, self._wlock, (_PUSH, 0, method, payload))
        except OSError as e:
            raise ConnectionError(str(e)) from e

    # ------------------------------------------------------------------ recv
    def _read_loop(self) -> None:
        try:
            while True:
                kind, msg_id, a, b = _recv_frame(self._sock)
                if kind == _REQUEST:
                    threading.Thread(
                        target=self._handle_request, args=(msg_id, a, b),
                        daemon=True).start()
                elif kind == _RESPONSE:
                    with self._inflight_lock:
                        fut = self._inflight.pop(msg_id, None)
                    if fut is not None:
                        ok, value = a, b
                        if ok:
                            fut.set_result(value)
                        else:
                            fut.set_exception(RemoteError(value))
                else:  # _PUSH
                    if self._push_handler is not None:
                        # off the read loop: a push handler that makes a
                        # synchronous call on THIS connection would otherwise
                        # deadlock (its response can never be read). A serial
                        # queue keeps per-connection push ordering (pubsub
                        # state transitions rely on it).
                        self._enqueue_push(a, b)
        except (ConnectionError, OSError, EOFError, pickle.UnpicklingError):
            pass
        finally:
            self.close()

    def _enqueue_push(self, method: str, payload: Any) -> None:
        if self._push_queue is None:
            import queue
            self._push_queue = queue.Queue()
            threading.Thread(target=self._push_loop, daemon=True).start()
        self._push_queue.put((method, payload))

    def _push_loop(self) -> None:
        while not self._closed.is_set():
            try:
                method, payload = self._push_queue.get(timeout=1.0)
            except Exception:
                continue
            try:
                self._push_handler(method, payload)
            except Exception:
                logger.exception("push handler failed for %s", method)

    def _handle_request(self, msg_id: int, method: str, payload: Any) -> None:
        try:
            if self._handler is None:
                raise RpcError(f"no handler for {method}")
            _maybe_fuzz()
            result = self._handler(self, method, payload)
            reply = (_RESPONSE, msg_id, True, result)
        except BaseException as e:  # noqa: BLE001 - errors cross the wire
            reply = (_RESPONSE, msg_id, False, e)
        try:
            _send_frame(self._sock, self._wlock, reply)
        except OSError:
            self.close()
        except Exception as e:
            # Result/exception wasn't picklable — still answer the caller so
            # its call() never hangs.
            try:
                _send_frame(self._sock, self._wlock,
                            (_RESPONSE, msg_id, False,
                             RpcError(f"unserializable {method} reply: {e!r}")))
            except OSError:
                self.close()

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._inflight_lock:
            inflight, self._inflight = self._inflight, {}
        for fut in inflight.values():
            if not fut.done():
                fut.set_exception(ConnectionError("connection closed"))
        if self._on_close is not None:
            cb, self._on_close = self._on_close, None
            try:
                cb(self)
            except Exception:
                logger.exception("on_close callback failed")

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class Server:
    """Threaded RPC server.

    ``handler(conn, method, payload)`` runs on a per-request thread; per-
    connection request *dispatch* order is preserved by the reader loop, and
    handlers that need strict ordering (actor queues) do their own sequencing.
    """

    def __init__(self, handler: Callable[[Connection, str, Any], Any],
                 host: str = "127.0.0.1", port: int = 0,
                 on_disconnect: Optional[Callable[[Connection], None]] = None):
        self._handler = handler
        self._on_disconnect = on_disconnect
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._conns: set[Connection] = set()
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        import time
        while not self._stopped.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                if self._stopped.is_set():
                    break
                # Transient failure (e.g. EMFILE): keep the server alive.
                logger.exception("accept() failed; retrying")
                time.sleep(0.1)
                continue
            conn = Connection(sock, handler=self._handler,
                              on_close=self._conn_closed)
            with self._lock:
                self._conns.add(conn)

    def _conn_closed(self, conn: Connection) -> None:
        with self._lock:
            self._conns.discard(conn)
        if self._on_disconnect is not None and not self._stopped.is_set():
            self._on_disconnect(conn)

    def connections(self) -> list[Connection]:
        with self._lock:
            return list(self._conns)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in self.connections():
            conn.close()


def connect(address: Tuple[str, int],
            push_handler: Optional[Callable[[str, Any], None]] = None,
            handler: Optional[Callable[[Connection, str, Any], Any]] = None,
            timeout: float = 30.0,
            on_close: Optional[Callable[[Connection], None]] = None) -> Connection:
    sock = socket.create_connection(address, timeout=timeout)
    sock.settimeout(None)
    return Connection(sock, handler=handler, push_handler=push_handler,
                      on_close=on_close)
