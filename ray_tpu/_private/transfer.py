"""Bulk object data plane: pipelined, multi-source, shm-direct pulls.

The inter-node twin of the control-plane fast path (docs/rpc_fastpath.md)
for large objects — the role the reference's ObjectManager/PullManager
pair plays (/root/reference/src/ray/object_manager/pull_manager.h:52,
object_manager.cc:338 chunked Push):

* **Pipelined windowed pulls** — up to ``object_pull_window`` chunk
  requests ride a pooled raylet connection concurrently, so a pull costs
  ``total/bandwidth`` instead of ``chunks * RTT``.
* **Multi-source striping** — when the location set holds several live
  copies, sources drain a shared offset queue (dynamic striping: a fast
  source simply serves more chunks).  A source dying or answering
  "absent" mid-transfer re-queues only its outstanding ranges onto the
  survivors; the transfer never restarts.
* **Shm-direct landing** — the destination buffer is allocated in the
  local shared-memory store up front and chunks are written at their
  final offsets; the object is sealed (published) once complete.  No
  whole-object heap copy exists on the client, and the pull budget
  accounts real shm bytes.  When the local store can't fit the object
  the engine degrades to a heap buffer instead of failing the get.
* **Budget admission** — multi-chunk pulls reserve their full size from
  a process-wide ``PullBudget`` before allocating.  An uncontended
  acquire keeps the already-fetched first chunk; a contended one drops
  it before parking (a parked waiter must hold no payload bytes).

Both the CoreWorker get path and the raylet's argument prefetch
(docs/object_transfer.md) drive this engine with their own store /
connection-cache / budget collaborators.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import rpc
from ray_tpu._private import runtime_metrics as rtm
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.logging_utils import get_logger
from ray_tpu.exceptions import ObjectStoreFullError

logger = get_logger("transfer")

# data-plane telemetry (docs/observability.md): bound once, no-ops when
# RAY_TPU_TELEMETRY=0
_M_PULLS = rtm.counter(
    "ray_tpu_pulls_total", "remote object pulls attempted by this process")
_M_PULL_BYTES = rtm.counter(
    "ray_tpu_pull_bytes_total", "object bytes pulled from remote nodes")
_M_CHUNK_RTT = rtm.histogram(
    "ray_tpu_pull_chunk_rtt_ms",
    "per-chunk request -> reply latency inside a pipelined pull (ms)")
_M_WINDOW = rtm.histogram(
    "ray_tpu_pull_inflight_window",
    "effective in-flight chunk window per pull (requests outstanding)",
    boundaries=rtm.COUNT_BOUNDARIES)
_M_FAILOVER = rtm.counter(
    "ray_tpu_pull_stripe_failovers_total",
    "mid-transfer source failures whose ranges re-queued onto survivors")
_M_BUDGET_WAIT = rtm.histogram(
    "ray_tpu_pull_budget_wait_ms",
    "time a multi-chunk pull waited for pull-budget admission (ms)")
_M_SOURCES = rtm.histogram(
    "ray_tpu_pull_sources",
    "distinct sources a completed multi-chunk pull striped across "
    "(collective broadcast fan-out rides this, docs/collective.md)",
    boundaries=rtm.COUNT_BOUNDARIES)


class PullBudget:
    """Admission control over concurrently buffered pull bytes (reference
    PullManager's bounded quota, pull_manager.h:52): N parallel gets of
    large objects queue here instead of overcommitting process memory.
    An object larger than the whole cap is admitted alone (capped at the
    full budget) so it can never deadlock."""

    def __init__(self, cap: int):
        self.cap = max(1, cap)
        self.used = 0
        self.cv = threading.Condition()
        self._waiters: deque = deque()  # FIFO tickets

    def acquire(self, n: int, deadline: Optional[float]) -> bool:
        n = min(n, self.cap)
        ticket = object()
        with self.cv:
            self._waiters.append(ticket)
            try:
                while True:
                    # strict FIFO: only the head ticket may admit — a big
                    # pull can't be starved by a stream of smaller ones
                    # slipping past it whenever they happen to fit
                    if self._waiters[0] is ticket and \
                            (self.used + n <= self.cap or self.used == 0):
                        self.used += n
                        return True
                    t = None if deadline is None \
                        else max(0.0, deadline - time.monotonic())
                    if t is not None and t <= 0:
                        return False
                    if not self.cv.wait(timeout=t if t is not None
                                        else 5.0) and deadline is not None:
                        return False
            finally:
                self._waiters.remove(ticket)
                self.cv.notify_all()

    def try_acquire(self, n: int) -> bool:
        """Non-blocking admit: True only when the quota (and FIFO head)
        admit immediately — the keep-the-first-chunk fast path."""
        return self.acquire(n, time.monotonic())

    def release(self, n: int) -> None:
        n = min(n, self.cap)
        with self.cv:
            self.used = max(0, self.used - n)
            self.cv.notify_all()


class ConnCache:
    """Tiny pooled-connection cache keyed by address (the raylet's analog
    of CoreWorker._owner_conn): one persistent duplex connection per
    peer instead of a TCP connect+close per pull."""

    def __init__(self):
        self._conns: Dict[Tuple[str, int], rpc.Connection] = {}
        self._lock = threading.Lock()

    def get(self, addr: Tuple[str, int]) -> rpc.Connection:
        addr = tuple(addr)
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
        conn = rpc.connect(addr, timeout=5.0)
        with self._lock:
            old = self._conns.get(addr)
            if old is not None and not old.closed:
                conn.close()
                return old
            self._conns[addr] = conn
        return conn

    def close(self) -> None:
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass


class PullOutcome:
    """Result of one ObjectPuller.pull.

    status   -- "ok" | "absent" | "error"
    data     -- serialized payload on "ok": a pinned shm memoryview when
                ``published`` (caller owns the single store pin), else a
                heap bytes-like (small object or store-full fallback)
    absent   -- node hexes that authoritatively answered "no copy"
                (callers drop those locations)
    transient-- at least one source failed on transport (retryable)
    """

    __slots__ = ("status", "data", "meta", "published", "absent",
                 "transient", "bytes", "duration_s", "nsources")

    def __init__(self, status: str, data=None, meta: int = 0,
                 published: bool = False, absent: Optional[set] = None,
                 transient: bool = False, nbytes: int = 0,
                 duration_s: float = 0.0, nsources: int = 0):
        self.status = status
        self.data = data
        self.meta = meta
        self.published = published
        self.absent = absent if absent is not None else set()
        self.transient = transient
        self.bytes = nbytes
        self.duration_s = duration_s
        self.nsources = nsources


class _SourceState:
    __slots__ = ("node", "conn", "outcome", "served")

    def __init__(self, node: str, conn: rpc.Connection):
        self.node = node
        self.conn = conn
        self.outcome = "ok"
        self.served = 0   # chunks this source actually delivered


class _PullState:
    """Offset queue + progress shared by the per-source loops of one pull.

    ``inflight`` counts offsets issued to some source and not yet written
    or re-queued.  A source that drains the queue must NOT exit while a
    peer still holds in-flight offsets: if that peer dies, its ranges
    come back to ``pending`` and the survivor has to pick them up — so
    idle sources park on ``cv`` instead of returning."""

    __slots__ = ("cv", "pending", "inflight", "done")

    def __init__(self, pending: deque, done: int):
        self.cv = threading.Condition()
        self.pending = pending
        self.inflight = 0
        self.done = done


class ObjectPuller:
    """The pull engine.  Collaborators:

    store        -- SharedMemoryStore chunks land in
    resolve_addr -- node_hex -> (host, port) or None
    get_conn     -- (host, port) -> pooled rpc.Connection (may raise)
    budget       -- PullBudget or None (no admission control)
    """

    def __init__(self, store, resolve_addr: Callable, get_conn: Callable,
                 budget: Optional[PullBudget] = None):
        self._store = store
        self._resolve = resolve_addr
        self._get_conn = get_conn
        self._budget = budget

    # ------------------------------------------------------------- public
    def pull(self, oid: ObjectID, sources: Sequence[str],
             deadline: Optional[float] = None,
             publish_small: bool = False) -> PullOutcome:
        """Pull one object from any/all of ``sources`` (node hexes).

        ``publish_small=True`` lands even single-chunk objects in the
        local store (the prefetch path wants a local copy; the get path
        prefers returning the bytes without store churn).

        Tracing (docs/observability.md): when the calling thread carries
        a sampled trace context (a serve decode pulling its KV handoff,
        a task fetching an argument), the pull lands as a ``pull`` span
        in that trace — the "handoff pull" hop of a disaggregated serve
        request is this very call."""
        from ray_tpu.util.tracing import tracing_helper as trh
        ctx = trh.current_context()
        if ctx is None or not trh.ctx_sampled(ctx):
            return self._pull_impl(oid, sources, deadline, publish_small)
        t0 = time.time()
        out = self._pull_impl(oid, sources, deadline, publish_small,
                              trace_ctx={"trace_id": ctx["trace_id"],
                                         "span_id": ctx.get("span_id"),
                                         "sampled": True})
        trh.record_span({
            "trace_id": ctx["trace_id"], "span_id": trh.new_span_id(),
            "parent_id": ctx.get("span_id"),
            "name": f"pull:{oid.hex()[:12]}", "kind": "pull",
            "start": t0, "dur_ms": round(out.duration_s * 1e3, 3),
            "status": trh.OK if out.status == "ok" else trh.ERROR,
            "attrs": {"bytes": out.bytes, "nsources": out.nsources,
                      "pull_status": out.status}})
        return out

    def _pull_impl(self, oid: ObjectID, sources: Sequence[str],
                   deadline: Optional[float] = None,
                   publish_small: bool = False,
                   trace_ctx: Optional[dict] = None) -> PullOutcome:
        t_start = time.monotonic()
        _M_PULLS.inc()
        chunk = CONFIG.object_transfer_chunk_bytes
        absent: set = set()
        transient = False
        conns: Dict[str, rpc.Connection] = {}

        # --- discovery: first chunk from the first answering source ---
        first = None
        for nh in sources:
            conn = self._conn_for(nh, conns)
            if conn is None:
                transient = True
                continue
            try:
                req = {"object_id": oid.binary(), "offset": 0,
                       "length": chunk, "timeout": 0.0, "oob": True}
                if trace_ctx is not None:
                    # the serving raylet joins the trace for the
                    # dispatch (rpc.py installs/pops "_trace_ctx")
                    req["_trace_ctx"] = trace_ctx
                res = conn.call("fetch_object_chunk", req,
                                timeout=self._chunk_timeout(deadline))
            except (ConnectionError, rpc.RemoteError, TimeoutError,
                    OSError):
                transient = True
                continue
            if res is None or not res.get("data"):
                absent.add(nh)
                continue
            first = (nh, res)
            break
        if first is None:
            status = "absent" if absent and not transient else "error"
            return PullOutcome(status, absent=absent, transient=transient,
                               duration_s=time.monotonic() - t_start)
        nh0, res0 = first
        total = int(res0["total"])
        meta = int(res0.get("meta", 0))
        data0 = res0["data"]

        if len(data0) >= total and not publish_small:
            _M_PULL_BYTES.inc(total)
            return PullOutcome("ok", data=data0, meta=meta, absent=absent,
                               nbytes=total,
                               duration_s=time.monotonic() - t_start,
                               nsources=1)

        # --- admission: reserve the full buffer before allocating ---
        acquired = False
        if self._budget is not None:
            if self._budget.try_acquire(total):
                acquired = True  # uncontended: keep the first chunk
            else:
                # parked waiters hold no payload bytes; re-fetching one
                # chunk later is cheaper than cap-exempt memory per waiter
                data0 = None
                t_wait = rtm.now()
                if not self._budget.acquire(total, deadline):
                    return PullOutcome(
                        "error", absent=absent, transient=True,
                        duration_s=time.monotonic() - t_start)
                _M_BUDGET_WAIT.observe_since(t_wait)
                acquired = True
        try:
            return self._pull_body(oid, total, meta, data0, chunk, nh0,
                                   list(sources), conns, absent, transient,
                                   deadline, t_start, trace_ctx)
        finally:
            if acquired:
                self._budget.release(total)

    # ------------------------------------------------------------ internal
    def _chunk_timeout(self, deadline: Optional[float]) -> float:
        base = CONFIG.raylet_rpc_timeout_s
        if deadline is None:
            return base
        return max(0.001, min(base, deadline - time.monotonic()))

    def _conn_for(self, nh: str, conns: Dict[str, rpc.Connection]
                  ) -> Optional[rpc.Connection]:
        conn = conns.get(nh)
        if conn is not None and not conn.closed:
            return conn
        addr = self._resolve(nh)
        if addr is None:
            return None
        try:
            conn = self._get_conn(tuple(addr))
        except (ConnectionError, OSError, TimeoutError):
            return None
        conns[nh] = conn
        return conn

    def _alloc_dest(self, oid: ObjectID, total: int, meta: int,
                    deadline: Optional[float]):
        """-> (buffer, kind): kind is "created" (unsealed shm create the
        caller fills + seals), "sealed" (another local pull/put finished
        first: pinned view, done), or "heap" (store can't fit it)."""
        grace = time.monotonic() + 10.0
        while True:
            try:
                # never evict to make room: eviction destroys bytes, and
                # a victim that lives only in shm (the raylet spills
                # lazily, under a threshold) would be LOST — the heap
                # fallback below is the pressure valve, not other
                # objects' only copies
                return (self._store.create(oid, total, meta=meta,
                                           allow_evict=False),
                        "created")
            except FileExistsError:
                # a sealed copy already local, or a concurrent local pull
                # in flight: wait for its seal instead of transferring the
                # same bytes twice
                res = self._store.get(oid, timeout=0.2)
                if res is not None:
                    return res[0], "sealed"
                now = time.monotonic()
                if now >= grace or \
                        (deadline is not None and now >= deadline):
                    # the other creator looks wedged (or died without the
                    # abort running): don't hang the get on it
                    return bytearray(total), "heap"
            except (ObjectStoreFullError, OSError):
                # secondary copies are best-effort: degrade to heap
                # assembly rather than failing the get (the raylet's
                # spill loop may free room for the next pull)
                return bytearray(total), "heap"

    def _pull_body(self, oid, total, meta, data0, chunk, nh0, sources,
                   conns, absent, transient, deadline, t_start,
                   trace_ctx=None):
        dest, kind = self._alloc_dest(oid, total, meta, deadline)
        if kind == "sealed":
            return PullOutcome("ok", data=dest, meta=meta, published=True,
                               absent=absent, nbytes=total,
                               duration_s=time.monotonic() - t_start)
        mv = dest if isinstance(dest, memoryview) else memoryview(dest)
        if data0:
            mv[:len(data0)] = data0
            ps = _PullState(deque(range(len(data0), total, chunk)),
                            len(data0))
        else:
            ps = _PullState(deque(range(0, total, chunk)), 0)

        # stripe across live copies, primary-answering source first
        stripe = [nh for nh in sources
                  if nh not in absent and nh != nh0]
        stripe.insert(0, nh0)
        stripe = stripe[:max(1, CONFIG.object_pull_max_sources)]
        # the window is per source, not divided across the stripe: each
        # source's pipeline depth is what hides its RTT, so halving it
        # when a second copy appears would throw away the very
        # parallelism striping exists for
        window = max(1, CONFIG.object_pull_window)
        _M_WINDOW.observe(min(window * len(stripe),
                              len(ps.pending) + (1 if data0 else 0)))

        states: List[_SourceState] = []
        for nh in stripe:
            conn = self._conn_for(nh, conns)
            if conn is not None:
                states.append(_SourceState(nh, conn))
        if not states:
            self._discard_dest(oid, dest, kind)
            return PullOutcome("error", absent=absent, transient=True,
                               duration_s=time.monotonic() - t_start)

        threads = []
        for st in states[1:]:
            t = threading.Thread(
                target=self._source_loop,
                args=(st, oid, mv, total, chunk, window, ps, deadline,
                      len(states) > 1, trace_ctx),
                daemon=True, name="pull-stripe")
            t.start()
            threads.append(t)
        # the first (primary) source runs on the calling thread: the
        # single-source common case spawns no threads at all
        self._source_loop(states[0], oid, mv, total, chunk, window, ps,
                          deadline, len(states) > 1, trace_ctx)
        for t in threads:
            t.join()

        for st in states:
            if st.outcome == "absent":
                absent.add(st.node)
            elif st.outcome == "error":
                transient = True

        if ps.done >= total:
            _M_PULL_BYTES.inc(total)
            # only sources that actually delivered chunks count: an
            # idle secondary (primary drained the queue first) must not
            # inflate the striping fan-out this records
            _M_SOURCES.observe(
                sum(1 for st in states if st.served > 0))
            data, published = self._publish_dest(oid, dest, mv, kind)
            if data is None:
                # sealed copy vanished before we could pin it (freed or
                # evicted instantly): the caller retries
                return PullOutcome(
                    "error", absent=absent, transient=True,
                    duration_s=time.monotonic() - t_start)
            return PullOutcome("ok", data=data, meta=meta,
                               published=published, absent=absent,
                               transient=transient, nbytes=total,
                               duration_s=time.monotonic() - t_start,
                               nsources=len(states))
        # incomplete: every source died or answered absent mid-transfer
        self._discard_dest(oid, dest, kind)
        status = "absent" if absent and not transient else "error"
        return PullOutcome(status, absent=absent, transient=transient,
                           duration_s=time.monotonic() - t_start,
                           nsources=len(states))

    @staticmethod
    def _make_sink(dest_slice, used: list):
        """Buffer sink for one chunk request (rpc.call_async): the reply's
        single out-of-band buffer is received straight into the chunk's
        shm destination slice — no per-chunk allocation, no copy."""
        def sink(lens):
            if len(lens) == 1 and 0 < lens[0] <= len(dest_slice):
                used.append(lens[0])
                return [dest_slice[:lens[0]]]
            return None  # unexpected shape: fall back to fresh storage
        return sink

    def _source_loop(self, st: _SourceState, oid, mv, total, chunk,
                     window, ps: _PullState, deadline,
                     striped: bool, trace_ctx=None) -> None:
        """Drain the shared offset queue through one source, keeping up
        to ``window`` chunk requests in flight.  On failure the source's
        outstanding offsets go back on the queue for the survivors."""
        inflight: deque = deque()  # (offset, future, t_sent, used)

        def fail(outcome: str, popped=None, popped_fut=None) -> None:
            st.outcome = outcome
            # withdraw the shm destinations first: a late reply on a
            # still-live conn must never land after the buffer is gone
            # (and duplicate writes from a survivor are racy only on
            # paper — chunk content is immutable).  abandon() also reaps
            # the futures from the pooled connection's inflight map so a
            # wedged-but-alive peer can't leak a window per retry.
            ids = [f._rpc_msg_id for _o, f, _t, _u in inflight]
            if popped_fut is not None:
                ids.append(popped_fut._rpc_msg_id)
            try:
                # the timeout is how long a reader mid-recv into one of
                # our sinks gets to finish before the connection is
                # closed to unwedge it: the conn is POOLED, so closing
                # fails every concurrent pull/RPC sharing it — give a
                # slow-but-live link time to drain one chunk (8 MiB at
                # ~1 MB/s), pay the wait only in the true-wedge case,
                # and never overshoot the caller's get(timeout=)
                drain = 10.0
                if deadline is not None:
                    drain = min(drain,
                                max(0.1, deadline - time.monotonic()))
                st.conn.abandon(ids, timeout=drain)
            except Exception:
                pass
            with ps.cv:
                if popped is not None:
                    ps.pending.appendleft(popped)
                    ps.inflight -= 1
                for o, _fut, _t, _u in inflight:
                    ps.pending.appendleft(o)
                    ps.inflight -= 1
                ps.cv.notify_all()
            if striped:
                _M_FAILOVER.inc()
                # lifecycle event (docs/observability.md): which source
                # died mid-stripe and who absorbed its ranges is exactly
                # the evidence that evaporates otherwise
                from ray_tpu._private import cluster_events as cev
                cev.emit(cev.TRANSFER_FAILOVER,
                         f"pull source {st.node[:8]} failed "
                         f"({outcome}); re-queued its outstanding "
                         "ranges on the survivors",
                         severity="WARNING", object_id=oid.hex(),
                         source_node=st.node, outcome=outcome)

        while True:
            while len(inflight) < window:
                with ps.cv:
                    off = ps.pending.popleft() if ps.pending else None
                    if off is not None:
                        ps.inflight += 1
                if off is None:
                    break
                length = min(chunk, total - off)
                payload = {"object_id": oid.binary(), "offset": off,
                           "length": length, "timeout": 0.0, "oob": True}
                if trace_ctx is not None:
                    # every chunk dispatch joins the trace, not just the
                    # discovery probe (the docs promise chunk fetches
                    # ride the context; ~70B per 8MiB chunk frame)
                    payload["_trace_ctx"] = trace_ctx
                used: List[int] = []
                try:
                    fut = st.conn.call_async(
                        "fetch_object_chunk", payload,
                        buffer_sink=self._make_sink(
                            mv[off:off + length], used))
                except Exception:
                    fail("error", popped=off)
                    return
                inflight.append((off, fut, time.monotonic(), used))
            if not inflight:
                with ps.cv:
                    if ps.done >= total or \
                            not (ps.pending or ps.inflight):
                        return
                    if not ps.pending:
                        # a peer still holds in-flight offsets: if it
                        # dies they re-queue — park here instead of
                        # abandoning the transfer to that peer's fate
                        ps.cv.wait(0.05)
                if deadline is not None and time.monotonic() >= deadline:
                    st.outcome = "error"
                    return
                continue
            off, fut, t_sent, used = inflight.popleft()
            if deadline is not None and time.monotonic() >= deadline:
                fail("error", popped=off, popped_fut=fut)
                return
            try:
                res = fut.result(self._chunk_timeout(deadline))
            except Exception:  # transport death, remote error, timeout
                fail("error", popped=off, popped_fut=fut)
                return
            data = res.get("data") if res else None
            if not data:
                # evicted/freed on this source mid-transfer: authoritative
                # for this source only — survivors pick up its ranges
                fail("absent", popped=off, popped_fut=fut)
                return
            _M_CHUNK_RTT.observe((time.monotonic() - t_sent) * 1000.0)
            st.served += 1
            if not used:
                # in-band reply (spilled-object path, legacy server):
                # land it at its offset ourselves
                mv[off:off + len(data)] = data
            with ps.cv:
                ps.done += len(data)
                ps.inflight -= 1
                if ps.done >= total:
                    ps.cv.notify_all()

    def _publish_dest(self, oid, dest, mv, kind):
        """Seal a completed shm create and swap to a pinned read view."""
        if kind != "created":
            return dest, False  # heap fallback: plain buffer
        mv.release()
        try:
            self._store.seal(oid)
        except KeyError:
            pass  # freed between write and seal: serve what we assembled
        res = self._store.get(oid, timeout=5.0)
        if res is None:
            # sealed copy vanished instantly (evicted/freed): the transfer
            # still succeeded — fall back to an unpinned error? No bytes
            # remain; report transient so the caller retries.
            return None, False
        return res[0], True

    def _discard_dest(self, oid, dest, kind) -> None:
        if kind != "created":
            return
        try:
            dest.release()
        except (BufferError, AttributeError):
            pass
        try:
            self._store.abort(oid)
        except Exception:
            pass
