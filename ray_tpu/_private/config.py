"""Central flag table for ray_tpu.

TPU-native analog of the reference's ``RAY_CONFIG`` macro system
(/root/reference/src/ray/common/ray_config_def.h: 188 flags, overridable via
``RAY_<NAME>`` env vars or ``ray.init(_system_config=...)``).  Here the table is
a single Python dataclass-of-record: every knob is declared once with a type and
default, is overridable via ``RAY_TPU_<NAME>`` environment variables, and can be
bulk-overridden at ``init(system_config={...})`` time (the dict is serialized to
every spawned daemon/worker process through its environment).
"""

from __future__ import annotations

import copy
import json
import os
import threading
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"
_SYSTEM_CONFIG_ENV = "RAY_TPU_SYSTEM_CONFIG"


class _Flag:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, type_: type, default: Any, doc: str = ""):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return raw.lower() in ("1", "true", "yes", "on")
        if self.type in (dict, list):
            return json.loads(raw)
        return self.type(raw)


_FLAG_TABLE: Dict[str, _Flag] = {}


def _declare(name: str, type_: type, default: Any, doc: str = "") -> None:
    _FLAG_TABLE[name] = _Flag(name, type_, default, doc)


# --------------------------------------------------------------------------- #
# Core runtime                                                                #
# --------------------------------------------------------------------------- #
_declare("inline_object_max_bytes", int, 100 * 1024,
         "Objects at most this size are inlined in RPC replies / the in-process "
         "memory store instead of the shared-memory store.")
_declare("object_store_memory_bytes", int, 2 * 1024**3,
         "Default per-node shared-memory object store capacity.")
_declare("object_store_fallback_dir", str, "",
         "Directory holding spilled-object files; empty means a spill_<node> "
         "dir inside the session dir (removed at raylet shutdown).")
_declare("object_store_dir", str, "/dev/shm",
         "Directory holding the shared-memory store segment (plasma "
         "convention: tmpfs, so big writes never hit disk writeback). "
         "Falls back to the session dir when missing or too small.")
_declare("object_store_prefault", bool, True,
         "Pre-touch the store segment's pages from a background thread at "
         "raylet start.  First-touch faults can be an order of magnitude "
         "slower than warm writes (VM memory ballooning / on-demand "
         "paging); prefaulting moves that cost off the put critical path.")
_declare("object_spill_threshold", float, 0.8,
         "Fraction of store capacity above which primary copies spill to disk.")
_declare("object_spill_fault", str, "",
         "Fault-injection seam for spill IO: 'unstable' fails every other "
         "spill write, 'slow' adds latency per spill (reference unstable/"
         "slow external-storage fakes, external_storage.py:587/608).")
_declare("object_spill_uri", str, "",
         "Storage URI objects spill to (file:// dir, mock:// for tests); "
         "empty means the local spill dir. Fallback-allocated primaries "
         "always stay on local disk, as in reference plasma "
         "(external_storage.py:72 ExternalStorage seam).")
_declare("object_spill_failure_rate", float, 0.0,
         "Deterministic fraction of spill writes that fail (storage-layer "
         "FlakyStorage; spilling retries on the next scan).")
_declare("object_spill_slow_ms", float, 0.0,
         "Injected latency per spill-storage operation in milliseconds.")
_declare("local_fs_capacity_threshold", float, 0.95,
         "Disk-usage fraction above which spill/fallback writes fail "
         "gracefully instead of filling the disk (reference "
         "local_fs_capacity_threshold, file_system_monitor.h).")
_declare("fs_monitor_test_usage_path", str, "",
         "Fault-injection seam: path of a file holding a float disk-usage "
         "fraction the filesystem monitor reads instead of statvfs.")
_declare("runtime_env_cache_dir", str, "",
         "Directory caching per-requirement-set pip venvs; empty means "
         "/tmp/ray_tpu_runtime_envs (reference URI cache, uri_cache.py).")
_declare("runtime_env_pip_find_links", str, "",
         "Local wheelhouse for pip runtime envs: installs run with "
         "--no-index --find-links here (zero-egress seam; unset uses the "
         "normal package index).")
_declare("object_transfer_chunk_bytes", int, 8 * 1024 * 1024,
         "Inter-node object pushes move in chunks of this size (bounds "
         "per-message memory; cf. reference object_manager chunked Push).")
_declare("scheduler_spill_threshold", float, 0.5,
         "Hybrid scheduling: local/packing preference holds until a node's "
         "critical-resource utilization crosses this fraction (cf. reference "
         "scheduler_spread_threshold, ray_config_def.h).")
_declare("worker_pool_max_idle", int, 8,
         "Max idle workers kept alive per node for lease reuse.")
_declare("worker_start_timeout_s", float, 30.0, "Worker process start timeout.")
_declare("worker_prefork", bool, True,
         "Fork python workers from a warm zygote process (one heavy "
         "interpreter+jax import per raylet instead of per worker). "
         "Venv-interpreter and cpp workers always exec.")
_declare("worker_lease_timeout_s", float, 30.0, "Worker lease RPC timeout.")
_declare("task_retry_delay_ms", int, 0,
         "Delay before resubmitting a task whose worker died (crash-loop "
         "backoff); 0 (default) resubmits immediately.")
_declare("max_direct_call_args_bytes", int, 100 * 1024,
         "Args bigger than this are put into the object store before submit.")
_declare("heartbeat_period_ms", int, 250,
         "Node daemon -> GCS resource/liveness report period.")
_declare("health_check_failure_threshold", int, 8,
         "Missed heartbeats before the GCS marks a node dead.")
_declare("rpc_fuzz_ms", float, 0.0,
         "Schedule fuzzing: jitter every RPC dispatch by up to this many "
         "milliseconds (uniform).  Race tooling — perturbs message "
         "interleavings the way TSAN-style schedule stressing does for "
         "threads; see tests/test_sched_fuzz.py.  0 disables.")
_declare("rpc_dispatch_threads", int, 256,
         "Size of the process-wide RPC dispatch pool (replaces thread-"
         "per-request).  Handlers that block (task pushes waiting on the "
         "executor FIFO, buffered actor seqs, parked lease grants) each "
         "hold one pool thread; the cap bounds runaway thread growth "
         "while staying far above any realistic in-flight handler "
         "count.  Values far below the default risk starving blocking "
         "handlers that wait on other pooled RPCs.")
_declare("rpc_inline_return_max_bytes", int, -1,
         "Task/actor returns at most this size travel inline in the "
         "push_tasks/actor_task reply instead of through the shared-"
         "memory store + location round trip.  -1 (default) follows "
         "inline_object_max_bytes.")
_declare("generator_backpressure_num_objects", int, -1,
         "num_returns=\"streaming\" backpressure: a generator task may "
         "have at most this many reported-but-unconsumed items in "
         "flight; past it the producing worker pauses until the "
         "consumer's next() acks consumption (the owner withholds item-"
         "report replies).  <= 0 disables (unbounded stream).  Stamped "
         "into each spec at submit time, so the OWNER's config governs "
         "the stream it consumes.")
_declare("task_submit_batch_max", int, 8,
         "Max task specs coalesced into one push_tasks frame per leased "
         "worker.  Specs carrying ObjectRef args always travel alone "
         "(their worker-side dependency resolution must observe earlier "
         "per-task acks).")
_declare("lease_keepalive_ms", int, 200,
         "How long a leased worker is held after its scheduling key's "
         "queue drains before the lease is returned to the raylet.  "
         "Back-to-back synchronous submissions reuse the warm lease and "
         "connection instead of paying lease_worker + connect + "
         "return_worker per task.  0 restores return-on-idle.")
_declare("timeout_scale", float, 1.0,
         "Multiplier applied to liveness/startup timeouts at resolution "
         "time (the _SCALED flags below).  Loaded hosts — CI sharing one "
         "core with the cluster under test — starve heartbeat threads "
         "for seconds at a time; scaling the patience beats tuning each "
         "timeout per box.  Set RAY_TPU_TIMEOUT_SCALE=4 in test envs.")
_declare("gcs_rpc_timeout_s", float, 30.0, "Client->GCS RPC timeout.")
_declare("gcs_snapshot_interval_s", float, 0.2,
         "Period of the GCS full-snapshot compaction tick (the WAL makes "
         "each mutation durable immediately; the snapshot only bounds "
         "replay length).")
_declare("gcs_wal_enabled", bool, True,
         "Per-mutation write-ahead journal next to the GCS snapshot "
         "(reference writes through to the Redis store client per "
         "mutation, store_client/redis_store_client.h:28).")
_declare("gcs_wal_fsync", bool, False,
         "fsync the GCS WAL after every record: survives host power loss "
         "at control-plane-latency cost; off, records survive process "
         "death but not kernel crash (matches Redis appendfsync "
         "everysec-style tradeoff).")
_declare("raylet_rpc_timeout_s", float, 30.0, "Client->node-daemon RPC timeout.")
_declare("cpp_worker_binary", str, "",
         "Path of the C++ worker binary spawned for language=cpp leases; "
         "empty means the stock build at ray_tpu/_core/cpp_worker. Point "
         "it at your own binary (csrc/cpp_functions.h "
         "RAY_TPU_CPP_FUNCTION) to expose custom C++ tasks.")
_declare("actor_creation_timeout_s", float, 60.0, "Actor __init__ readiness timeout.")
_declare("memory_monitor_refresh_ms", int, 250,
         "Period of the per-node host-memory monitor; 0 disables it.")
_declare("memory_usage_threshold", float, 0.95,
         "Host-memory fraction above which the worker-killing policy engages.")
_declare("memory_monitor_test_usage_path", str, "",
         "Fault-injection seam: path of a file holding a float usage "
         "fraction the memory monitor reads instead of kernel counters.")
_declare("task_oom_retries", int, 15,
         "Separate retry budget for tasks whose worker was OOM-killed "
         "(reference task_oom_retries, ray_config_def.h:104-111); regular "
         "max_retries is not consumed by OOM kills.")
_declare("fetch_fail_timeout_s", float, 60.0,
         "Grace window for transient fetch failures (unreachable raylet on "
         "an alive node) before an owned object is declared lost and lineage "
         "reconstruction kicks in (cf. reference "
         "fetch_fail_timeout_milliseconds).")
_declare("lineage_max_bytes", int, 64 * 1024**2,
         "Cap on pinned lineage (task specs kept for object reconstruction).")
_declare("free_objects_period_ms", int, 100,
         "Batching period for releasing store objects whose refcount hit zero.")
_declare("pull_memory_cap_bytes", int, 512 * 1024**2,
         "Admission cap on the total bytes of concurrently in-flight remote "
         "object pulls per process (reference PullManager's bounded pull "
         "quota, pull_manager.h:52); pulls beyond it queue FIFO.")
_declare("object_pull_window", int, 8,
         "Pipelined pull depth: chunk requests kept in flight per source "
         "(a striped pull keeps up to window * nsources in flight).  1 "
         "restores one-chunk-per-RTT serial pulls (the A/B baseline in "
         "benchmarks/object_transfer_perf.py); docs/object_transfer.md.")
_declare("object_pull_max_sources", int, 4,
         "Max nodes a single pull stripes chunk ranges across when the "
         "location set holds multiple live copies.  1 disables striping.")
_declare("locality_aware_scheduling", bool, True,
         "Weigh argument bytes already local when placing leases: a "
         "lease request carrying argument locations may be redirected to "
         "the feasible node holding the most argument bytes (reference "
         "locality-aware lease policy, locality_data_provider).")
_declare("locality_min_arg_bytes", int, 1024 * 1024,
         "Arguments at least this size participate in locality-aware "
         "placement and raylet-side prefetch; below it transfer cost is "
         "noise next to lease latency.")
_declare("object_prefetch_enabled", bool, True,
         "Raylet-side argument prefetch: a granted lease request's "
         "missing large arguments start pulling into local shm "
         "concurrently with worker lease/startup, so transfer overlaps "
         "scheduling instead of serializing after it.")
_declare("prefetch_pin_ttl_s", float, 60.0,
         "Safety-net lifetime of raylet prefetch pins: pins not released "
         "by their lease's return (e.g. the lease request timed out or "
         "the task was cancelled before dispatch) drop after this long.")
_declare("drain_grace_s", float, 30.0,
         "Default grace window of a drain_node request (spot/preemption "
         "notice): the draining raylet stops granting leases, waits this "
         "long for in-flight task leases to finish, then evacuates "
         "primary object copies to surviving nodes "
         "(docs/fault_tolerance.md).")
_declare("evacuation_enabled", bool, True,
         "Drain-time object evacuation: a draining raylet ships its "
         "sealed store + spilled objects to surviving nodes over the "
         "transfer plane so a graceful preemption loses zero objects.")
_declare("evac_pin_ttl_s", float, 300.0,
         "Lifetime of the receiving raylet's pin on an evacuated copy: "
         "long enough for owners to learn the new location (first fetch "
         "after the source dies), bounded so orphaned copies whose "
         "owners never come back stop occupying shm.")
_declare("gcs_max_evacuated_objects", int, 8192,
         "Cap on the GCS evacuated-object location table (oldest "
         "entries rotate out; an expired hint degrades to lineage "
         "reconstruction, never to a wrong answer).")
_declare("gcs_evac_ttl_s", float, 600.0,
         "Expiry of evacuated-object location hints: owners that "
         "haven't consulted a hint within this window fall back to "
         "reconstruction.")
_declare("object_reconstruct_max_attempts", int, 10,
         "Per-object budget on lineage reconstruction resubmits, on top "
         "of the task's own max_retries: a flapping node repeatedly "
         "losing the same object must converge to ObjectLostError "
         "instead of resubmitting forever.")
_declare("log_to_driver", bool, True, "Forward worker stdout/stderr to the driver.")
_declare("task_events_buffer_size", int, 10000,
         "Ring-buffer capacity of per-worker task state-transition events.")
_declare("task_events_flush_interval_ms", int, 500,
         "Period at which workers flush task events to the GCS task table.")
_declare("gcs_max_task_events", int, 100000,
         "Max per-task records the GCS task table keeps before GC.")
_declare("telemetry_enabled", bool, True,
         "Always-on runtime telemetry (_private/runtime_metrics.py): "
         "hot-path counters/histograms in every daemon and worker plus "
         "the per-process flusher publishing them to the GCS KV "
         "metrics/ namespace.  Also overridable as RAY_TPU_TELEMETRY=0 "
         "(the bench kill-switch); disabling swaps instruments for "
         "no-op stubs at binding time, so it must be off before the "
         "process imports the instrumented modules.")
_declare("telemetry_flush_interval_ms", int, 2000,
         "Period of the runtime-metrics flusher pushing per-process "
         "snapshots to the GCS KV (dashboard /metrics, list_metrics); "
         "only metrics that changed since the last flush are re-sent.")
_declare("events_enabled", bool, True,
         "Cluster event plane (_private/cluster_events.py): typed "
         "lifecycle events from every daemon/worker into the GCS event "
         "table, plus the per-process flight-recorder ring feeding "
         "crash dossiers.  Also overridable as RAY_TPU_EVENTS=0 (the "
         "bench kill switch, mirroring RAY_TPU_TELEMETRY); disabling "
         "drops emits after one global read and never starts the "
         "flusher.")
_declare("event_ring_size", int, 512,
         "Per-process flight-recorder ring capacity: the last N events "
         "(lifecycle + ring-only task breadcrumbs) kept in memory and "
         "dumped atomically to the per-worker flight file each flush, "
         "so a crash dossier can show what the process was doing.")
_declare("events_flush_interval_ms", int, 500,
         "Period of the cluster-events flusher batching this process's "
         "events to the GCS table and rewriting its flight file.")
_declare("gcs_max_cluster_events", int, 20000,
         "Max events the GCS cluster event table retains (sharded "
         "rotation, oldest dropped first).")
_declare("gcs_events_max_bytes", int, 4 * 1024 * 1024,
         "Byte budget of the GCS cluster event table (JSON-serialized "
         "record sizes); the hard retention gate — oldest events are "
         "evicted across shards until the table fits.")
_declare("gcs_max_dossiers", int, 128,
         "Max crash dossiers the GCS retains (FIFO eviction).")
_declare("dossier_log_tail_bytes", int, 16384,
         "Bytes of worker stdout/stderr tail harvested into a crash "
         "dossier.")
_declare("node_unhealthy_mem_frac", float, 0.92,
         "Host-memory fraction in a raylet health snapshot above which "
         "the GCS emits a NODE_UNHEALTHY event for the node.")
_declare("node_unhealthy_store_frac", float, 0.95,
         "Object-store occupancy fraction above which a node is "
         "reported unhealthy.")
_declare("node_unhealthy_lag_ms", float, 2000.0,
         "Raylet heartbeat-loop event-loop lag above which a node is "
         "reported unhealthy (a starved daemon thread: the node may "
         "miss liveness deadlines soon).")
_declare("daemon_connect_retry_s", float, 10.0,
         "Bounded retry window for a freshly spawned daemon's FIRST "
         "connection to its GCS (backoff 50ms doubling to 1s; daemon "
         "call sites only — raylet/dashboard/monitor pass "
         "connect_retry=True, interactive clients stay fail-fast).  "
         "Under heavy box load the GCS subprocess can publish its "
         "address file before its accept loop keeps up with the "
         "connection burst; one refused connect must not kill the "
         "raylet (the load-dependent startup-race flake).  Scaled by "
         "timeout_scale.")
_declare("step_stats_enabled", bool, True,
         "Training performance plane (_private/step_stats.py): per-step "
         "phase clocks, the cross-rank GCS step table + straggler "
         "detection, and the goodput ledger.  Also overridable as "
         "RAY_TPU_STEP_STATS=0 (the bench kill switch, mirroring "
         "RAY_TPU_TELEMETRY / RAY_TPU_EVENTS); disabling hands train "
         "loops a shared no-op clock.")
_declare("step_stats_flush_interval_ms", int, 500,
         "Period of the per-rank step-stats flusher batching step "
         "reports to the GCS step table (never an RPC on the step "
         "path).")
_declare("step_stats_timeline_steps", int, 256,
         "Per-run cap on STEP timeline slices recorded into the GCS "
         "task table (the STREAM_ITEM cap discipline: first N steps "
         "per run per rank).")
_declare("gcs_step_stats_max_steps", int, 512,
         "Per-run step retention in the GCS step table (oldest steps "
         "rotate out first).")
_declare("gcs_max_step_runs", int, 16,
         "Max training runs the GCS step table retains "
         "(oldest-touched evicted first).")
_declare("straggler_mad_k", float, 4.0,
         "Straggler detection: a rank whose step time exceeds the "
         "cross-rank median + k * MAD (by at least straggler_min_ms) "
         "edge-triggers a TRAIN_STRAGGLER event.")
_declare("straggler_min_ms", float, 20.0,
         "Absolute floor on the straggler overshoot: ms-scale steps "
         "jitter by scheduler noise, and median + k*MAD alone would "
         "fire on microsecond skew in a tight gang.")
_declare("tracing_enabled", bool, True,
         "Distributed request tracing plane (util/tracing/"
         "tracing_helper.py): trace roots at every serve ingress, "
         "sampled roots at task/actor submission, cross-process span "
         "propagation and the GCS span table.  Also overridable as "
         "RAY_TPU_TRACING=0 (the bench kill switch, mirroring "
         "RAY_TPU_TELEMETRY / RAY_TPU_EVENTS); disabling makes every "
         "root/span call a no-op after one cached flag read.")
_declare("trace_sample_rate", float, 0.1,
         "Fraction of traces whose spans are recorded, decided by a "
         "deterministic hash of the trace id (every process reaches "
         "the same verdict for the same id with no coordination).  "
         "Serve ingresses always open a root context for SLO "
         "accounting; this rate gates span recording and task/actor "
         "submission roots.  1.0 records everything, 0 disables "
         "recording while keeping SLO counters.")
_declare("trace_flush_interval_ms", int, 500,
         "Period of the per-process span-buffer flusher batching "
         "finished spans to the GCS span table (never an RPC on the "
         "request path).")
_declare("trace_buffer_size", int, 2048,
         "Per-process bound on buffered-but-unflushed spans; past it "
         "new spans are dropped (counted) rather than growing memory "
         "behind a dead GCS.")
_declare("trace_stream_span_items", int, 16,
         "Per-stream cap on per-yield item marker spans recorded into "
         "a sampled trace (the STREAM_ITEM cap discipline, scaled down "
         "— tracing wants the pacing shape, not every token).")
_declare("gcs_max_traces", int, 512,
         "Max traces the GCS span table retains (sharded rotation, "
         "oldest dropped first).")
_declare("gcs_traces_max_bytes", int, 8 * 1024 * 1024,
         "Byte budget of the GCS span table (JSON-serialized span "
         "sizes); the hard retention gate alongside the trace count.")
_declare("gcs_trace_max_spans", int, 256,
         "Per-trace span cap in the GCS span table (first and last "
         "halves survive, like the task table's per-record event cap).")
_declare("serve_slo_ttft_ms", float, 2000.0,
         "Serve SLO target: time-to-first-token budget per request "
         "(ms).  Completed requests are classified against it into "
         "ray_tpu_serve_slo_good/violation{pool,slo=ttft} counters "
         "with exemplar trace ids on the slowest requests; <= 0 "
         "disables the dimension.")
_declare("debug_locks", bool, False,
         "Lock-order sanitizer (analysis/lock_sanitizer.py): swap "
         "threading.Lock/RLock created by instrumented runtime modules "
         "for wrappers that record the per-thread acquisition-order "
         "graph and raise at the FIRST A->B/B->A inversion.  Debug "
         "tool (set RAY_TPU_DEBUG_LOCKS=1); the chaos and compiled-DAG "
         "suites run under it.")
_declare("debug_channels", bool, False,
         "Shm-ring protocol checker (analysis/channel_check.py): "
         "assert single-writer / seq-word-last / cumulative-ack "
         "discipline on every experimental/channel.py publish and "
         "ack.  Debug tool (set RAY_TPU_DEBUG_CHANNELS=1); enabled in "
         "the chaos and compiled-DAG suites.")
_declare("serve_slo_tpot_ms", float, 200.0,
         "Serve SLO target: inter-token latency budget (ms/token past "
         "the first) for streaming requests; <= 0 disables the "
         "dimension.")
_declare("metrics_history_enabled", bool, True,
         "Metrics-history plane kill switch (RAY_TPU_METRICS_HISTORY "
         "env wins): the GCS folds every metrics KV write into the "
         "bounded multi-resolution history table and runs the "
         "recovery-SLO auditor over the event stream.  Off, the GCS "
         "keeps only latest-snapshot metrics (pre-history behavior).")
_declare("metrics_history_resolutions", str, "1:120,10:180,60:120",
         "History retention geometry: comma-separated res_s:slots "
         "rings per series.  The default keeps 2 min at 1 s, 30 min "
         "at 10 s and 2 h at 60 s; within a bucket the newest flusher "
         "snapshot wins (snapshots are cumulative, so last-write IS "
         "the downsample).")
_declare("gcs_metrics_history_max_series", int, 512,
         "Max metric series (distinct metrics/{name}/{ident} keys) the "
         "history table retains; the longest-idle series is evicted "
         "first, like the metrics KV staleness sweep.")
_declare("gcs_metrics_history_max_bytes", int, 8 * 1024 * 1024,
         "Byte budget of the metrics history table (raw payload bytes "
         "across all rings); the hard retention gate alongside the "
         "series cap — oldest point dropped first.")
_declare("gcs_max_recovery_episodes", int, 256,
         "Max closed recovery episodes the auditor retains (drain / "
         "failover / heal); per-kind totals and violation counters "
         "survive rotation like the event table's counts_by_type.")
_declare("gcs_recovery_max_bytes", int, 512 * 1024,
         "Byte budget of the recovery-episode table (JSON-serialized "
         "episode sizes), alongside the episode count cap.")
_declare("recovery_slo_drain_s", float, 0.0,
         "Drain SLO: budget for NODE_PREEMPTING -> NODE_DRAINED per "
         "episode (s).  <= 0 uses each episode's advertised grace "
         "window — the budget the raylet promised to finish inside.")
_declare("recovery_slo_failover_s", float, 120.0,
         "Failover SLO: budget from the first failure event "
         "(NODE_PREEMPTING/NODE_DEAD) to TRAIN_GANG_RECOVERY (s); "
         "<= 0 disables classification.")
_declare("recovery_slo_heal_s", float, 90.0,
         "Pool-heal SLO: budget from REPLICA_RETIRED to the next "
         "AUTOSCALE target change for that deployment (s); <= 0 "
         "disables classification.")

# --------------------------------------------------------------------------- #
# TPU / device model                                                          #
# --------------------------------------------------------------------------- #
_declare("tpu_chips_per_host", int, 0,
         "Override detected TPU chip count for the node resource report.")
_declare("tpu_slice_name", str, "",
         "Pod-slice identifier this host belongs to (e.g. 'v5e-16/abc'). Hosts "
         "of one slice form an atomic scheduling bundle.")
_declare("mesh_default_axes", dict, {"data": -1},
         "Default mesh axis layout used by JaxTrainer when none is given.")

# --------------------------------------------------------------------------- #
# Collectives                                                                 #
# --------------------------------------------------------------------------- #
_declare("collective_rendezvous_timeout_s", float, 60.0,
         "Timeout for host-collective group rendezvous via the GCS KV store.")
_declare("collective_op_timeout_s", float, 120.0, "Host collective op timeout.")
_declare("collective_chunk_bytes", int, 8 * 1024 * 1024,
         "Ring-collective segment size: tensors are segmented into pieces "
         "of this size that pipeline through the ring (step k+1's send "
         "overlaps step k's recv+reduce) and bound per-message memory; "
         "also the shm-channel slot payload size, so each same-node link "
         "maps nslots * this many bytes of the store segment "
         "(docs/collective.md).")
_declare("collective_inflight_segments", int, 4,
         "Pipelined take-request depth per collective link: how many "
         "segment requests a rank keeps in flight to its ring "
         "predecessor (each needs one staging buffer of "
         "collective_chunk_bytes during reduce phases).")
_declare("collective_small_max_bytes", int, 32 * 1024,
         "Tensors at most this size use the latency-optimal recursive-"
         "doubling allreduce (log2(N) rounds of whole-tensor exchanges) "
         "instead of the bandwidth-optimal segmented ring.")
_declare("collective_shm_enabled", bool, True,
         "Exchange collective segments between same-node ranks over "
         "shared-memory ring channels (experimental/channel.py) instead "
         "of TCP loopback.")
_declare("collective_shm_slot_bytes", int, 1024 * 1024,
         "Slot payload size of same-node collective shm ring channels; "
         "groups with colocated ranks segment by "
         "min(collective_chunk_bytes, this), so a link costs "
         "~slots * this of store segment instead of slots * chunk "
         "(8 MiB chunks would charge ~50 MB per link).")
_declare("collective_shm_slots", int, 6,
         "Ring-slot count of each same-node collective shm channel "
         "(per-directed-pair buffering; >= inflight window + 2 keeps "
         "the pipelined ring from blocking on ring credit).")
_declare("collective_flat_shm", bool, True,
         "Single-node groups allreduce through the flat shared-memory "
         "arena (each rank publishes its input once into the node store "
         "and reduces its own chunk from all peers' mapped views) "
         "instead of the segmented ring, when ~2.5x the group's tensor "
         "footprint fits the store (docs/collective.md).")
_declare("collective_hierarchical", bool, True,
         "Two-level collectives when ranks are colocated: intra-node "
         "reduce over shm to a per-node leader, inter-node ring among "
         "leaders, intra-node broadcast of the result.")
_declare("collective_quant_block", int, 256,
         "Elements per fp32 scale block of the int8 wire codec "
         "(quantize='int8'): wire bytes per fp32 segment are "
         "n + 4*ceil(n/block), so larger blocks compress harder at the "
         "cost of coarser per-block dynamic range (docs/collective.md).")
_declare("collective_quant_min_bytes", int, 64 * 1024,
         "Tensors at most this size skip wire quantization even when "
         "quantize= is requested: small ops are latency-bound, and the "
         "encode/decode passes cost more than the bytes they save.")
_declare("collective_bucket_bytes", int, 32 * 1024 * 1024,
         "sync_gradients splits each per-dtype gradient bucket into "
         "sub-buckets of at most this size, each its own allreduce: "
         "with async_op=True early buckets reduce while the caller is "
         "still computing later gradients (backward overlap), and even "
         "the barrier path pipelines bucket k+1's encode behind bucket "
         "k's ring (docs/collective.md).")
_declare("collective_topology", bool, True,
         "Slice-aware collective scheduling: ranks carrying a "
         "tpu_slice_name label are grouped by slice, reduced "
         "intra-slice first (ICI shard_map collectives when a mesh is "
         "registered, host links otherwise), and only slice leaders "
         "ring over DCN (docs/collective.md).")
_declare("collective_sim_dcn_mbps", float, 0.0,
         "Debug/benchmark: pace every published collective segment to "
         "this bandwidth (MiB/s of ENCODED bytes; 0 = off).  Models a "
         "bytes-limited DCN link on boxes whose loopback wire is "
         "really CPU, so the quantized-allreduce A/B measures the "
         "regime the codec targets (the object_spill_slow_ms "
         "injection precedent; benchmarks/collective_perf.py --quant).")
_declare("collective_bcast_store_min_bytes", int, 4 * 1024 * 1024,
         "broadcast() payloads at least this size move over the object-"
         "transfer data plane instead of the ring — when the group spans "
         "more than one node: the source puts the tensor once and every "
         "rank pulls it multi-source-striped, each completed rank "
         "becoming an additional source (docs/object_transfer.md).  "
         "Same-node-only groups always use the shm ring chain.")

# --------------------------------------------------------------------------- #
# Libraries                                                                   #
# --------------------------------------------------------------------------- #
_declare("sharded_ckpt_keep", int, 2,
         "Sharded-checkpoint fallback chain depth "
         "(ray_tpu/train/sharded/executor.py): each gang checkpoint "
         "embeds pointers to this many predecessors, so a restore whose "
         "newest shards were lost with an ungracefully killed node "
         "walks back one interval per lost checkpoint instead of "
         "failing the run (docs/train_sharded.md).")
_declare("sharded_ckpt_pull_timeout_s", float, 30.0,
         "Per-shard pull timeout on sharded-checkpoint restore: how "
         "long a fresh gang rank waits for its parameter shard to "
         "stripe in from the object-transfer plane before the restore "
         "falls back to the previous checkpoint in the chain.")
_declare("serve_http_host", str, "127.0.0.1",
         "Default serve proxy bind host (HTTPOptions overrides per app).")
_declare("serve_http_port", int, 8000,
         "Default serve proxy bind port (HTTPOptions overrides per app).")
_declare("serve_controller_loop_ms", int, 250,
         "Serve controller reconcile period (replica set convergence "
         "and autoscaling cadence).")
_declare("serve_handoff_quantize", bool, False,
         "Encode cross-host PrefillHandoff KV with the block-scaled "
         "int8 wire codec (util/collective/quant.py) before "
         "ray_tpu.put: ~3.9x fewer handoff bytes on the wire at the "
         "cost of one encode/decode pass per handoff.  Greedy decode "
         "must stay token-exact (the disagg smoke test gates it).")
_declare("serve_prefix_cache_pages", int, 0,
         "KV pages each paged LLM engine may retain as a shared prompt-"
         "prefix cache after prefill (docs/serve_frontdoor.md).  A new "
         "prompt whose page-aligned prefix digest-chain matches a "
         "retained run skips re-prefilling those pages (suffix-only "
         "prefill over borrowed read-only pages).  0 disables "
         "retention; refcounted LRU eviction keeps the budget.")
_declare("serve_prefix_index_max", int, 4096,
         "Router-side prefix index bound (frontdoor/prefix.py): max "
         "digest -> replica entries a DeploymentHandle keeps from the "
         "controller's load-publish path; LRU past it.")
_declare("serve_rerole_enabled", bool, False,
         "SLO-driven elastic re-roling: the serve controller watches "
         "per-pool ttft/tpot SLO violation deltas (trace plane) for "
         "<base>-prefill/<base>-decode pairs and re-roles one replica "
         "from the healthy pool into the burning one (drain -> "
         "re-role -> rejoin).  Off by default; request_rerole() still "
         "works for forced episodes.")
_declare("serve_rerole_interval_s", float, 30.0,
         "How often the controller evaluates the re-roling policy "
         "(seconds between trace_stats polls).")
_declare("serve_rerole_cooldown_s", float, 120.0,
         "Minimum time between re-roling episodes per deployment pair: "
         "the pool must re-stabilize (and the SLO counters re-baseline) "
         "before the policy may move another replica.")
_declare("serve_rerole_min_violations", int, 20,
         "New SLO violations (since the last poll, on the dominant "
         "dimension) required before a re-role triggers — below it the "
         "signal is noise, not a burning pool.")
_declare("recovery_slo_rerole_s", float, 60.0,
         "Re-role SLO: budget from SERVE_REROLE to SERVE_REROLE_DONE "
         "(donor drained + receiver pool healthy again, s); <= 0 "
         "disables classification.")

# --------------------------------------------------------------------------- #
# RL podracer executor (docs/rl_podracer.md)                                  #
# --------------------------------------------------------------------------- #
_declare("podracer_backpressure_fragments", int, 2,
         "Per-rollout-actor staleness bound for the podracer streaming "
         "ingest: at most this many yielded-but-unconsumed fragments may "
         "be in flight per actor (stamped into "
         "generator_backpressure_num_objects at stream submit time). "
         "<= 0 leaves the stream unbounded — free-running actors whose "
         "fragments can be arbitrarily stale.")
_declare("podracer_prefetch_depth", int, 4,
         "Depth of the learner-side host prefetch queue that overlaps "
         "fragment download/deserialization with the compiled learner "
         "step.  A full queue blocks the per-actor ingest threads, which "
         "stops acking the streams and lets generator backpressure pause "
         "the producers — queue depth + per-actor window together bound "
         "end-to-end staleness.")
_declare("podracer_weight_quantize", bool, False,
         "Publish podracer weight versions int8 block-quantized over the "
         "wire (the PR 16 Int8Codec; block size collective_quant_block). "
         "~4x fewer broadcast bytes per sync at a bounded blockmax/254 "
         "round-trip error; actors dequantize on pull.")
_declare("podracer_weight_keep_versions", int, 2,
         "Published weight versions the learner keeps alive (object-store "
         "refs) beyond the newest.  Older refs are dropped so the store "
         "reclaims them; actors that poll past a dropped version jump "
         "straight to the newest (the version-skip rule).")
_declare("podracer_sync_every_steps", int, 1,
         "Learner steps between weight-version publishes.  1 publishes "
         "after every optimizer step (lowest staleness, most broadcast "
         "traffic); larger values amortize the put() + KV bump.")
_declare("recovery_slo_rl_actor_s", float, 60.0,
         "RL actor-replacement SLO: budget from RL_ACTOR_LOST to the "
         "replacement's RL_ACTOR_JOINED for that fleet slot (s); <= 0 "
         "disables classification.")


class Config:
    """Process-wide resolved flag values.

    Resolution order (highest wins):
      1. explicit ``set()`` calls / ``init(system_config=...)``
      2. ``RAY_TPU_<NAME>`` environment variables
      3. the JSON blob in ``RAY_TPU_SYSTEM_CONFIG`` (how daemons inherit the
         driver's overrides)
      4. declared defaults
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._overrides: Dict[str, Any] = {}
        # bumped on every override mutation: hot paths cache resolved flag
        # values keyed on this (rpc._maybe_fuzz) instead of re-resolving
        # through the lock + env lookup per call
        self._gen = 0
        blob = os.environ.get(_SYSTEM_CONFIG_ENV)
        if blob:
            try:
                for k, v in json.loads(blob).items():
                    if k in _FLAG_TABLE:
                        self._overrides[k] = v
            except (ValueError, TypeError):
                pass

    def __getattr__(self, name: str) -> Any:
        flag = _FLAG_TABLE.get(name)
        if flag is None:
            raise AttributeError(f"unknown ray_tpu config flag: {name!r}")
        value = None
        found = False
        with self._lock:
            if name in self._overrides:
                value = self._overrides[name]
                found = True
        if not found:
            raw = os.environ.get(_ENV_PREFIX + name.upper())
            if raw is not None:
                try:
                    value = flag.parse(raw)
                    found = True
                except (ValueError, TypeError):
                    pass
        if not found:
            value = flag.default
        if name in _SCALED_FLAGS:
            scale = self.timeout_scale
            if scale != 1.0:
                value = flag.type(value * scale)
        return copy.deepcopy(value) if isinstance(value, (dict, list)) \
            else value

    def generation(self) -> int:
        """Monotonic override-mutation counter (see __init__)."""
        return self._gen

    def set(self, name: str, value: Any) -> None:
        if name not in _FLAG_TABLE:
            raise KeyError(f"unknown ray_tpu config flag: {name!r}")
        with self._lock:
            self._overrides[name] = value
            self._gen += 1

    def update(self, overrides: Dict[str, Any]) -> None:
        for k, v in (overrides or {}).items():
            self.set(k, v)

    def copy_overrides(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._overrides)

    def set_overrides(self, overrides: Dict[str, Any]) -> None:
        with self._lock:
            self._overrides = dict(overrides)
            self._gen += 1

    def overrides_env_blob(self) -> str:
        """Serialized overrides to pass to child processes via env."""
        with self._lock:
            return json.dumps(self._overrides)

    def snapshot(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _FLAG_TABLE}


# liveness/startup patience flags timeout_scale multiplies (NOT data-
# plane semantics like spill thresholds or batch waits)
_SCALED_FLAGS = frozenset({
    "health_check_failure_threshold",
    "daemon_connect_retry_s",
    "worker_start_timeout_s",
    "worker_lease_timeout_s",
    "actor_creation_timeout_s",
    "gcs_rpc_timeout_s",
    "raylet_rpc_timeout_s",
    "fetch_fail_timeout_s",
    "collective_rendezvous_timeout_s",
    "collective_op_timeout_s",
})

CONFIG = Config()


def flag_docs() -> Dict[str, str]:
    return {f.name: f.doc for f in _FLAG_TABLE.values()}
