"""Chaos-injection utilities for failure testing.

Analog of the reference's NodeKiller / get_and_run_node_killer
(/root/reference/python/ray/_private/test_utils.py:1301): a background
loop that periodically kills a random alive raylet (via the raylet's
``die`` chaos RPC), sparing a protected set (the head node), so recovery
paths — task retries, actor restarts, lineage reconstruction, PG
rescheduling — are exercised under unplanned loss instead of only
scripted removals.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional, Sequence, Tuple

from ray_tpu._private import rpc
from ray_tpu._private.logging_utils import get_logger
from ray_tpu.runtime.gcs import GcsClient

logger = get_logger("chaos")


class NodeKiller:
    """Kills a random unprotected alive node every ``interval_s``."""

    def __init__(self, gcs_address: Tuple[str, int],
                 protected_node_ids: Sequence[str] = (),
                 interval_s: float = 5.0,
                 max_kills: Optional[int] = None,
                 seed: int = 0):
        self._gcs_address = tuple(gcs_address)
        self._protected = set(protected_node_ids)
        self._interval = interval_s
        self._max_kills = max_kills
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.kills: list = []  # node_id hexes, in kill order

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        gcs = GcsClient(self._gcs_address)
        try:
            while not self._stop.wait(self._interval):
                if self._max_kills is not None and \
                        len(self.kills) >= self._max_kills:
                    return
                try:
                    self.kill_one(gcs)
                except Exception:
                    logger.exception("node kill attempt failed")
        finally:
            gcs.close()

    def kill_one(self, gcs: Optional[GcsClient] = None) -> Optional[str]:
        """Kill one random eligible node now; returns its id or None."""
        own = gcs is None
        if own:
            gcs = GcsClient(self._gcs_address)
        try:
            nodes = [n for n in gcs.call("list_nodes", timeout=10)
                     if n["alive"] and n["node_id"] not in self._protected]
            if not nodes:
                return None
            victim = self._rng.choice(nodes)
            # a node the GCS hasn't noticed dying yet refuses the connect:
            # that's not a kill, don't spend budget on it
            if victim["node_id"] in self.kills:
                return None
            try:
                conn = rpc.connect(tuple(victim["address"]), timeout=5.0)
            except (ConnectionError, TimeoutError, OSError):
                return None  # already down
            try:
                try:
                    conn.call("die", {}, timeout=5)
                except (ConnectionError, rpc.RpcError, TimeoutError,
                        OSError):
                    pass  # dying mid-reply is success
            finally:
                conn.close()
            self.kills.append(victim["node_id"])
            logger.warning("chaos: killed node %s", victim["node_id"][:8])
            return victim["node_id"]
        finally:
            if own:
                gcs.close()


def kill_component(address: Tuple[str, int]) -> bool:
    """One-shot kill of any daemon exposing the ``die`` RPC."""
    try:
        conn = rpc.connect(tuple(address), timeout=5.0)
        try:
            conn.call("die", {}, timeout=5)
        finally:
            conn.close()
        return True
    except (ConnectionError, rpc.RpcError, TimeoutError, OSError):
        return True  # it died before replying — that's the point
