"""Per-node log tailer: worker stdout/stderr -> GCS pubsub -> driver.

Analog of /root/reference/python/ray/_private/log_monitor.py (tails the
session log dir and publishes lines over GCS pubsub so drivers can print
them with `ray.init(log_to_driver=True)` semantics).
"""

from __future__ import annotations

import glob
import os
import threading
from typing import Callable, Dict, Optional

LOG_CHANNEL = "worker_logs"
_SCAN_PERIOD_S = 0.5
_MAX_LINES_PER_BATCH = 200


class LogMonitor:
    """Thread tailing `<session>/logs/worker-*.{out,err}`.

    ``job_of`` maps a worker-id prefix to the job currently leasing that
    worker, so each published batch carries a job_id and drivers print only
    their own workers' output (reference routes log lines by job the same
    way).
    """

    def __init__(self, session_dir: str, gcs, node_id: str,
                 job_of: Optional[Callable[[str], Optional[str]]] = None):
        self._log_dir = os.path.join(session_dir, "logs")
        self._gcs = gcs
        self._node_id = node_id
        self._job_of = job_of
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-monitor")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(_SCAN_PERIOD_S):
            try:
                self._scan()
            except Exception:
                pass  # never let a log hiccup kill the monitor

    def _scan(self) -> None:
        for path in glob.glob(os.path.join(self._log_dir, "worker-*")):
            base = os.path.basename(path)
            if not (base.endswith(".out") or base.endswith(".err")):
                continue
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            offset = self._offsets.get(path, 0)
            if size <= offset:
                continue
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size - offset)
            # only publish complete lines; carry partials to the next scan
            last_nl = data.rfind(b"\n")
            if last_nl < 0:
                continue
            self._offsets[path] = offset + last_nl + 1
            lines = data[:last_nl].decode("utf-8", "replace").splitlines()
            worker, stream = base[len("worker-"):].rsplit(".", 1)
            job_id = self._job_of(worker) if self._job_of else None
            for i in range(0, len(lines), _MAX_LINES_PER_BATCH):
                try:
                    self._gcs.call("publish", {
                        "channel": LOG_CHANNEL,
                        "message": {
                            "node_id": self._node_id,
                            "worker": worker,
                            "job_id": job_id,
                            "stream": stream,
                            "lines": lines[i:i + _MAX_LINES_PER_BATCH],
                        }})
                except Exception:
                    return  # GCS unreachable; retry next scan


def tail_file(path: str, nbytes: int) -> str:
    """Last ``nbytes`` of a log file, decoded leniently — the crash-
    dossier harvest path (raylet reads a dead worker's stdout/stderr)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - nbytes))
            data = f.read(nbytes)
        return data.decode("utf-8", "replace")
    except OSError:
        return ""


def print_to_driver(message: dict, job_id: Optional[str] = None) -> None:
    """Driver-side subscriber: prefix lines like the reference does.

    ``job_id``: this driver's job — batches tagged with a *different* job are
    dropped (untagged batches print everywhere, e.g. prestarted workers).
    """
    import sys
    msg_job = message.get("job_id")
    if job_id is not None and msg_job is not None and msg_job != job_id:
        return
    out = sys.stderr if message.get("stream") == "err" else sys.stdout
    prefix = f"({message.get('worker', '?')[:8]})"
    for line in message.get("lines", []):
        print(f"{prefix} {line}", file=out)
