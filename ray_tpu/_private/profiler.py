"""In-process sampling profiler: folded stacks over a time window.

Analog of the reference's on-demand py-spy CPU profiling of any worker
(/root/reference/python/ray/dashboard/modules/reporter/reporter_agent.py:253
``CpuProfilingManager``) without the external binary: every daemon and
worker answers a ``profile`` RPC by sampling ``sys._current_frames()``
for the requested window and returning flamegraph-ready folded stacks
(``a;b;c count`` lines, collapse format), so ``ray-tpu profile`` can
flame any live process in the cluster.
"""

from __future__ import annotations

import sys
import time
from typing import Dict


def sample_folded(duration_s: float = 2.0,
                  interval_s: float = 0.01,
                  max_depth: int = 60) -> Dict[str, int]:
    """Sample every thread's stack for ``duration_s``; returns
    {folded_stack: samples}. Runs inside the target process (the RPC
    thread doing the sampling excludes itself)."""
    me = sys._getframe()  # marker: skip the sampler's own thread
    counts: Dict[str, int] = {}
    end = time.monotonic() + max(0.05, duration_s)
    interval_s = max(0.001, interval_s)
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            f = frame
            stack = []
            skip = False
            while f is not None and len(stack) < max_depth:
                if f is me:
                    skip = True
                    break
                code = f.f_code
                fname = code.co_filename.rsplit("/", 1)[-1]
                stack.append(f"{code.co_name} ({fname}:{f.f_lineno})")
                f = f.f_back
            if skip or not stack:
                continue
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
        time.sleep(interval_s)
    return counts


def dump_stacks(max_depth: int = 60) -> Dict[str, str]:
    """One instantaneous stack per live thread, keyed by thread name
    (``dump_stacks`` RPC: a stalled process answers in microseconds,
    no gdb, no sampling window).  The dumping thread excludes itself."""
    import threading
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    out: Dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        if tid == me:
            continue
        stack = traceback.format_stack(frame)[-max_depth:]
        out[f"{names.get(tid, '?')} ({tid})"] = "".join(stack)
    return out


def stacks_text(threads: Dict[str, str]) -> str:
    """Terminal rendering of a dump_stacks() reply."""
    lines = []
    for name in sorted(threads):
        lines.append(f"--- thread {name} ---")
        lines.append(threads[name].rstrip())
    return "\n".join(lines)


def folded_text(counts: Dict[str, int]) -> str:
    """Flamegraph collapse format, hottest first."""
    return "\n".join(
        f"{stack} {n}" for stack, n in
        sorted(counts.items(), key=lambda kv: -kv[1]))


def top_summary(counts: Dict[str, int], limit: int = 20) -> str:
    """Human-readable leaf-frame ranking for terminal output."""
    leaves: Dict[str, int] = {}
    total = 0
    for stack, n in counts.items():
        leaf = stack.rsplit(";", 1)[-1]
        leaves[leaf] = leaves.get(leaf, 0) + n
        total += n
    lines = [f"{total} samples"]
    for leaf, n in sorted(leaves.items(), key=lambda kv: -kv[1])[:limit]:
        lines.append(f"  {100 * n / max(1, total):5.1f}%  {leaf}")
    return "\n".join(lines)
