"""In-process sampling profiler: folded stacks over a time window.

Analog of the reference's on-demand py-spy CPU profiling of any worker
(/root/reference/python/ray/dashboard/modules/reporter/reporter_agent.py:253
``CpuProfilingManager``) without the external binary: every daemon and
worker answers a ``profile`` RPC by sampling ``sys._current_frames()``
for the requested window and returning flamegraph-ready folded stacks
(``a;b;c count`` lines, collapse format), so ``ray-tpu profile`` can
flame any live process in the cluster.

Frames are keyed ``co_name (file)`` — WITHOUT the line number.  A hot
line shifting by one line between captures (an edit, a different branch
of the same loop) used to split its count across two keys and break
capture-to-capture comparison; line-level detail is preserved
separately for the LEAF frame only (where the samples actually land)
under the reserved ``LEAF_LINES_KEY`` entry, and ``top_summary`` shows
the hottest line as a detail column.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Dict, Optional, Tuple

# reserved entry in a sample_folded() result carrying per-leaf line
# tallies: {leaf_frame: {"lineno": count}}.  Rides the same dict so the
# profile RPC's wire shape stays one JSON-able mapping; every consumer
# goes through split_leaf_detail() first.
LEAF_LINES_KEY = "__leaf_lines__"


def split_leaf_detail(counts: Dict[str, Any]
                      ) -> Tuple[Dict[str, int], Dict[str, Dict[str, int]]]:
    """Split a sample_folded() result into (stack counts, leaf line
    tallies).  Accepts pre-detail captures (no reserved key) and merged
    dicts transparently."""
    if LEAF_LINES_KEY not in counts:
        return counts, {}
    clean = {k: v for k, v in counts.items() if k != LEAF_LINES_KEY}
    detail = counts.get(LEAF_LINES_KEY) or {}
    return clean, detail if isinstance(detail, dict) else {}


def sample_folded(duration_s: float = 2.0,
                  interval_s: float = 0.01,
                  max_depth: int = 60) -> Dict[str, Any]:
    """Sample every thread's stack for ``duration_s``; returns
    {folded_stack: samples} plus the ``LEAF_LINES_KEY`` detail entry.
    Runs inside the target process (the RPC thread doing the sampling
    excludes itself)."""
    me = sys._getframe()  # marker: skip the sampler's own thread
    counts: Dict[str, int] = {}
    leaf_lines: Dict[str, Dict[str, int]] = {}
    end = time.monotonic() + max(0.05, duration_s)
    interval_s = max(0.001, interval_s)
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            f = frame
            stack = []
            leaf_line = None
            skip = False
            while f is not None and len(stack) < max_depth:
                if f is me:
                    skip = True
                    break
                code = f.f_code
                fname = code.co_filename.rsplit("/", 1)[-1]
                if leaf_line is None:
                    leaf_line = f"{fname}:{f.f_lineno}"
                stack.append(f"{code.co_name} ({fname})")
                f = f.f_back
            if skip or not stack:
                continue
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
            per = leaf_lines.setdefault(stack[0], {})
            per[leaf_line] = per.get(leaf_line, 0) + 1
        time.sleep(interval_s)
    if counts:
        counts[LEAF_LINES_KEY] = leaf_lines
    return counts


def profile_capture(duration_s: float, *, device: bool = False,
                    out_dir: Optional[str] = None) -> Dict[str, Any]:
    """One profile window: folded host stacks always; with ``device``,
    a ``jax.profiler`` device trace captured over the SAME window (the
    trace brackets the host sampling, so gang ranks' host and device
    views line up).  Returns {"folded": counts[, "device_trace": dir,
    "device_error": reason]} — the ``profile`` RPC's gang-fanout shape
    (``ray-tpu profile --group --device``)."""
    if not device:
        return {"folded": sample_folded(duration_s)}
    started = False
    err = None
    try:
        import jax
        if jax.devices()[0].platform != "tpu":
            err = ("--device needs a TPU backend; this process is on "
                   f"{jax.devices()[0].platform!r} — folded host stacks "
                   "only (docs/observability.md)")
        else:
            if out_dir is None:   # only once a trace will actually start
                import tempfile
                out_dir = tempfile.mkdtemp(prefix="ray-tpu-devtrace-")
            jax.profiler.start_trace(out_dir)
            started = True
    except Exception as e:
        err = f"device trace failed to start: {e!r}"
    counts = sample_folded(duration_s)
    if started:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            err = f"device trace failed to stop: {e!r}"
            started = False
    return {"folded": counts,
            "device_trace": out_dir if started else None,
            "device_error": err}


def dump_stacks(max_depth: int = 60) -> Dict[str, str]:
    """One instantaneous stack per live thread, keyed by thread name
    (``dump_stacks`` RPC: a stalled process answers in microseconds,
    no gdb, no sampling window).  The dumping thread excludes itself."""
    import threading
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    out: Dict[str, str] = {}
    for tid, frame in sys._current_frames().items():
        if tid == me:
            continue
        stack = traceback.format_stack(frame)[-max_depth:]
        out[f"{names.get(tid, '?')} ({tid})"] = "".join(stack)
    return out


def stacks_text(threads: Dict[str, str]) -> str:
    """Terminal rendering of a dump_stacks() reply."""
    lines = []
    for name in sorted(threads):
        lines.append(f"--- thread {name} ---")
        lines.append(threads[name].rstrip())
    return "\n".join(lines)


def folded_text(counts: Dict[str, Any]) -> str:
    """Flamegraph collapse format, hottest first."""
    clean, _ = split_leaf_detail(counts)
    return "\n".join(
        f"{stack} {n}" for stack, n in
        sorted(clean.items(), key=lambda kv: -kv[1]))


def merge_folded(dest: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Accumulate one capture into another (gang profile merging),
    keeping the leaf-line detail coherent."""
    clean, detail = split_leaf_detail(src)
    dest_detail = dest.setdefault(LEAF_LINES_KEY, {})
    for stack, n in clean.items():
        dest[stack] = dest.get(stack, 0) + n
    for leaf, lines in detail.items():
        per = dest_detail.setdefault(leaf, {})
        for line, n in lines.items():
            per[line] = per.get(line, 0) + n


def top_summary(counts: Dict[str, Any], limit: int = 20) -> str:
    """Human-readable leaf-frame ranking for terminal output, with the
    hottest source line of each leaf as a detail column (the line
    number lives only here — keys stay line-stable across captures)."""
    clean, detail = split_leaf_detail(counts)
    leaves: Dict[str, int] = {}
    total = 0
    for stack, n in clean.items():
        leaf = stack.rsplit(";", 1)[-1]
        leaves[leaf] = leaves.get(leaf, 0) + n
        total += n
    lines = [f"{total} samples"]
    for leaf, n in sorted(leaves.items(), key=lambda kv: -kv[1])[:limit]:
        where = ""
        per = detail.get(leaf)
        if per:
            line, ln = max(per.items(), key=lambda kv: kv[1])
            where = f"  [{line} {100 * ln / max(1, n):.0f}%]"
        lines.append(f"  {100 * n / max(1, total):5.1f}%  {leaf}{where}")
    return "\n".join(lines)
