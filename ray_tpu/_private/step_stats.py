"""Training performance plane: per-step phase clock, cross-rank step
aggregation + straggler detection, and the goodput ledger.

The three observability planes that exist (metrics, timeline,
events/dossiers — docs/observability.md) watch the *runtime*; this module
watches the *training job*.  Distributed TPU training lives or dies on
keeping every chip busy every step (Podracer, arXiv:2104.06272): a single
straggling rank or a slow host phase silently taxes the whole gang
through the gradient allreduce, and "why is MFU 0.51 and not 0.55" is
unanswerable without a per-step phase breakdown.  Four pieces:

* **StepClock** — a per-rank, per-step phase timer the train loop drives
  (``bench.py`` timing discipline: phases are cut by explicit fences,
  ``jax.block_until_ready`` for device compute).  Every step decomposes
  into ``data_wait / host_dispatch / device_compute / grad_allreduce /
  optimizer / checkpoint`` slices, published three ways: runtime-metrics
  histogram families (``ray_tpu_train_step_ms`` / ``_phase_ms``,
  sub-ms-resolution buckets), STEP timeline slices on a synthetic
  ``step-<run>-r<rank>`` task record (first
  ``step_stats_timeline_steps`` per run, the STREAM_ITEM cap
  discipline) with a shared ``trace_id`` per step, and a batched
  per-step report to the GCS step table.

* **GcsStepStatsTable** — the GCS-side aggregation point (sharded-
  retention philosophy of ``GcsClusterEventTable``: bounded runs x
  bounded steps, ephemeral, never WALed).  When every rank of a step
  has reported, it computes cross-rank skew and **edge-triggers** a
  typed ``TRAIN_STRAGGLER`` event (rank, step, slowest phase, overshoot
  vs ``median + k * MAD``) into the PR 9 event plane — a degraded rank
  names itself instead of just dragging the allreduce.

* **GoodputLedger** — per-run accounting (init/compile time, productive
  step time, checkpoint time, idle/restart gaps, tokens, model FLOPs ->
  MFU and goodput fraction), pushed to the GCS at run end and exposed
  via ``experimental.state.training_summary()`` / ``ray-tpu summary
  training`` / the dashboard Training tab.  ``bench.py`` consumes the
  same ledger so BENCH rows carry ``goodput`` and a phase breakdown
  instead of recomputing MFU by hand.

* **merged_profile_trace** — folds per-rank ``profile`` RPC captures
  (``ray-tpu profile --group``) into one Perfetto-compatible trace
  keyed by rank, correlated with the step slices.

Kill switch: ``RAY_TPU_STEP_STATS=0`` (or
``CONFIG.step_stats_enabled=False``) mirrors ``RAY_TPU_TELEMETRY`` /
``RAY_TPU_EVENTS``: ``step_clock()`` hands back a shared no-op clock, so
an instrumented loop costs one no-op method call per phase and nothing
is recorded anywhere (benchmarks/telemetry_overhead.py --step-stats
holds the on-cost to the same <= 3% bar).
"""

from __future__ import annotations

import os
import statistics
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.config import CONFIG
from ray_tpu._private import runtime_metrics as rtm

# canonical phase order: timeline sub-slices stack in this order inside a
# step, and the goodput ledger reports totals keyed by these names
PHASES = ("data_wait", "host_dispatch", "device_compute",
          "grad_allreduce", "optimizer", "checkpoint")

# ms-scale steps need sub-ms resolution at the low end (a healthy
# data_wait is tens of microseconds) while checkpoint phases reach tens
# of seconds — same reasoning as the byte-scale handoff buckets
# (serve/llm.py): geometric coverage of the realistic range, anchored
# where the interesting distinctions live.
STEP_PHASE_MS_BOUNDARIES: Tuple[float, ...] = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
    50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
    30000.0)

_M_STEP_MS = rtm.histogram_family(
    "ray_tpu_train_step_ms",
    "end-to-end train-step wall time per run (ms)", tag_key="run",
    boundaries=STEP_PHASE_MS_BOUNDARIES)
_M_PHASE_MS = rtm.histogram_family(
    "ray_tpu_train_phase_ms",
    "train-step phase wall time (ms): data_wait / host_dispatch / "
    "device_compute / grad_allreduce / optimizer / checkpoint",
    tag_key="phase", boundaries=STEP_PHASE_MS_BOUNDARIES)
_C_STEPS = rtm.counter("ray_tpu_train_steps_total",
                       "train steps completed on this process")


def enabled() -> bool:
    """Kill switch: RAY_TPU_STEP_STATS env wins, then the config flag."""
    raw = os.environ.get("RAY_TPU_STEP_STATS")
    if raw is not None:
        return raw.strip().lower() not in ("0", "false", "no", "off")
    return CONFIG.step_stats_enabled


# ------------------------------------------------------------- ledger
class GoodputLedger:
    """Per-run, per-rank time accounting.

    Buckets every second of the run's wall clock: init (before the first
    step), compile (explicitly noted — the first dispatch usually), the
    productive step time (sum of step clocks), checkpoint time spent
    OUTSIDE steps (an in-step checkpoint phase counts inside its step
    and is reported in the phase breakdown either way), and the
    remainder — idle/restart gaps.  With ``tokens`` and model-FLOPs
    context it derives MFU and the goodput fraction."""

    def __init__(self, run_id: str, *, group: str = "", rank: int = 0,
                 world: int = 1, flops_per_token: float = 0.0,
                 peak_flops: float = 0.0):
        self.run_id = run_id
        self.group = group
        self.rank = rank
        self.world = world
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        self.t_start = time.time()
        self._t0 = rtm.now()
        self.init_ms = 0.0
        self._init_done = False
        self.compile_ms = 0.0
        self.steps = 0
        self.productive_ms = 0.0
        self.checkpoint_outside_ms = 0.0
        self.tokens = 0
        self.phase_ms: Dict[str, float] = {}
        self.finished = False
        self.wall_ms = 0.0

    def note_init_done(self) -> None:
        """Everything before this point was setup (worker spawn, mesh
        build, state init) — called automatically by the first
        ``begin()`` if never called explicitly."""
        if not self._init_done:
            self._init_done = True
            self.init_ms = (rtm.now() - self._t0) * 1000.0

    def note_compile_ms(self, ms: float) -> None:
        self.note_init_done()
        self.compile_ms += ms

    def note_step(self, step_ms: float, phases: Dict[str, float],
                  tokens: int) -> None:
        self.note_init_done()
        self.steps += 1
        self.productive_ms += step_ms
        self.tokens += int(tokens)
        for name, ms in phases.items():
            self.phase_ms[name] = self.phase_ms.get(name, 0.0) + ms

    def note_outside_phase(self, name: str, ms: float) -> None:
        if name == "checkpoint":
            self.checkpoint_outside_ms += ms
        self.phase_ms[name] = self.phase_ms.get(name, 0.0) + ms

    def finish(self) -> dict:
        if not self.finished:
            self.finished = True
            self.wall_ms = (rtm.now() - self._t0) * 1000.0
        return self.summary()

    def summary(self) -> dict:
        wall_ms = self.wall_ms if self.finished \
            else (rtm.now() - self._t0) * 1000.0
        accounted = (self.init_ms + self.compile_ms + self.productive_ms
                     + self.checkpoint_outside_ms)
        idle_ms = max(0.0, wall_ms - accounted)
        prod_s = self.productive_ms / 1000.0
        tokens_per_s = self.tokens / prod_s if prod_s > 0 else 0.0
        mfu = 0.0
        if prod_s > 0 and self.peak_flops > 0:
            mfu = (self.flops_per_token * self.tokens) / prod_s \
                / self.peak_flops
        return {
            "run": self.run_id, "group": self.group, "rank": self.rank,
            "world": self.world, "ts_start": self.t_start,
            "wall_ms": round(wall_ms, 3),
            "init_ms": round(self.init_ms, 3),
            "compile_ms": round(self.compile_ms, 3),
            "productive_ms": round(self.productive_ms, 3),
            "checkpoint_ms": round(
                self.phase_ms.get("checkpoint", 0.0), 3),
            "idle_ms": round(idle_ms, 3),
            "steps": self.steps,
            "tokens": self.tokens,
            "tokens_per_s": round(tokens_per_s, 1),
            "phase_ms": {k: round(v, 3)
                         for k, v in sorted(self.phase_ms.items())},
            "goodput": round(self.productive_ms / wall_ms, 4)
            if wall_ms > 0 else 0.0,
            "mfu": round(mfu, 4),
            "finished": self.finished,
        }


# ---------------------------------------------------------- run context
class _RunContext:
    """One training run on one rank: ledger + reporter + timeline cap.

    The per-step GCS reports buffer here and a small flusher thread
    ships them on ``step_stats_flush_interval_ms`` cadence (the
    metrics/events flusher philosophy: never an RPC on the step path)."""

    def __init__(self, run_id: str, *, group: str = "", rank: int = 0,
                 world: int = 1, flops_per_token: float = 0.0,
                 peak_flops: float = 0.0, tokens_per_step: int = 0,
                 sink: Optional[Callable[[List[dict]], Any]] = None,
                 meta: Optional[dict] = None):
        self.run_id = run_id
        self.group = group
        self.rank = rank
        self.world = world
        self.tokens_per_step = tokens_per_step
        self.ledger = GoodputLedger(
            run_id, group=group, rank=rank, world=world,
            flops_per_token=flops_per_token, peak_flops=peak_flops)
        self._sink = sink
        self._meta = dict(meta or {})
        self._meta_sent = False
        self._buf: List[dict] = []
        self._buf_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.step_no = 0
        self.timeline_steps = 0
        self.clock = StepClock(self)
        self._step_hist = _M_STEP_MS.get(run_id)
        # resolved once per run: CONFIG attribute resolution (lock +
        # env lookup) and the core-worker import are too heavy for the
        # per-step path (benchmarks/telemetry_overhead.py --step-stats)
        self._timeline_cap = CONFIG.step_stats_timeline_steps
        self._events_sink = None
        self._events_resolved = False

    # -- reporting ---------------------------------------------------------
    def _report_step(self, step: int, step_ms: float,
                     phases: Dict[str, float], ts_end: float) -> None:
        if self._sink is None:
            return
        rep = {"run": self.run_id, "group": self.group,
               "rank": self.rank, "world": self.world, "step": step,
               "ts": ts_end, "step_ms": round(step_ms, 3),
               "phases": phases}
        with self._buf_lock:
            if not self._meta_sent:
                rep["meta"] = self._meta
                self._meta_sent = True
            self._buf.append(rep)
            if self._thread is None and not self._stop.is_set():
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name="step-stats-flush")
                self._thread.start()

    def flush(self) -> None:
        with self._buf_lock:
            batch, self._buf = self._buf, []
        if batch and self._sink is not None:
            try:
                self._sink(batch)
            except Exception:
                # GCS away: re-queue bounded (one table's worth), like
                # the event recorder — a control-plane outage must not
                # grow rank memory by the step rate
                with self._buf_lock:
                    self._buf = (batch + self._buf)[
                        -max(16, CONFIG.gcs_step_stats_max_steps):]

    def _flush_loop(self) -> None:
        period = max(0.05, CONFIG.step_stats_flush_interval_ms / 1000.0)
        while not self._stop.wait(period):
            self.flush()
        self.flush()

    def _push_summary(self, summary: dict) -> None:
        if self._sink is None:
            return
        try:
            self._sink([{"run": self.run_id, "group": self.group,
                         "rank": self.rank, "world": self.world,
                         "summary": summary}])
        except Exception:
            pass

    def close(self) -> dict:
        self.clock._finalize_open_step()
        summary = self.ledger.finish()
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=2.0)
        self.flush()
        self._push_summary(summary)
        if self.timeline_steps:
            # the gang's actors are killed right after the driver sees
            # "done": push the STEP timeline events now instead of
            # betting on the task-event flusher's next 500ms tick
            events = (self._events_sink or (None,))[0]
            if events is not None:
                try:
                    events.flush()
                except Exception:
                    pass
        if self.ledger.steps:
            # same race for the train metrics families: a short run's
            # worker can die before its 2s metrics flusher tick
            try:
                rtm.flush_now()
            except Exception:
                pass
        return summary

    # -- timeline ----------------------------------------------------------
    def _record_timeline(self, step: int, step_ms: float,
                         phases: Dict[str, float]) -> None:
        if self.timeline_steps >= self._timeline_cap:
            return
        if not self._events_resolved:
            self._events_resolved = True
            self._events_sink = _events_buffer()
        events, node_id, worker_id = self._events_sink or (None, "", "")
        if events is None:
            return
        self.timeline_steps += 1
        try:
            events.record(
                f"step-{self.run_id}-r{self.rank}", "STEP",
                name=f"train_step:{self.run_id}",
                step=step, dur_ms=round(step_ms, 3),
                phases=phases,
                trace_id=f"step-{self.run_id}:{step}",
                node_id=node_id, worker_id=worker_id)
        except Exception:
            pass


def _events_buffer():
    """The connected process's task-event buffer (timeline sink), or
    (None, ...) standalone — bench.py runs without a cluster."""
    try:
        from ray_tpu.runtime import core_worker as cw
        worker = cw.get_global_worker()
        if worker is None or getattr(worker, "events", None) is None:
            return None, "", ""
        return (worker.events, getattr(worker, "node_id", ""),
                worker.worker_id.hex())
    except Exception:
        return None, "", ""


# ------------------------------------------------------------ step clock
class _PhaseCtx:
    __slots__ = ("_clock", "_name", "_t0")

    def __init__(self, clock: "StepClock", name: str):
        self._clock = clock
        self._name = name

    def __enter__(self):
        self._t0 = rtm.now()
        return self

    def __exit__(self, *exc):
        self._clock.record_phase(self._name,
                                 (rtm.now() - self._t0) * 1000.0)
        return False


class StepClock:
    """Per-step phase timer for one rank's train loop.

    ``begin()`` opens a step (auto-finalizing a still-open previous one,
    so a loop that only calls ``begin()`` + phases still records every
    step); ``phase(name)`` is a context manager cutting one phase;
    ``end(tokens=...)`` closes the step and publishes metrics, the
    timeline slice and the GCS report.  Phase timing relies on the
    caller fencing device work (``jax.block_until_ready`` inside the
    ``device_compute`` phase — the bench.py discipline); an unfenced
    dispatch attributes device time to whichever phase next blocks on
    the device queue."""

    def __init__(self, run: _RunContext):
        self._run = run
        self._open = False
        self._t_begin = 0.0
        self._phases: Dict[str, float] = {}

    # -- step lifecycle ----------------------------------------------------
    def begin(self) -> "StepClock":
        self._finalize_open_step()
        self._run.ledger.note_init_done()
        self._open = True
        self._t_begin = rtm.now()
        self._phases = {}
        return self

    def phase(self, name: str) -> _PhaseCtx:
        if not self._open:
            self.begin()
        return _PhaseCtx(self, name)

    def record_phase(self, name: str, ms: float) -> None:
        if self._open:
            self._phases[name] = self._phases.get(name, 0.0) + ms
        else:
            self._run.ledger.note_outside_phase(name, ms)
        _M_PHASE_MS.observe(name, ms)

    def end(self, tokens: Optional[int] = None) -> Optional[float]:
        """Close the step; returns its wall ms (None if no step open)."""
        if not self._open:
            return None
        self._open = False
        step_ms = (rtm.now() - self._t_begin) * 1000.0
        run = self._run
        step = run.step_no
        run.step_no += 1
        n_tokens = run.tokens_per_step if tokens is None else tokens
        run.ledger.note_step(step_ms, self._phases, n_tokens)
        run._step_hist.observe(step_ms)
        _C_STEPS.inc()
        run._record_timeline(step, step_ms, self._phases)
        run._report_step(step, step_ms, self._phases, time.time())
        self._phases = {}
        return step_ms

    def _finalize_open_step(self) -> None:
        if self._open:
            self.end()


class _NoopClock:
    """Shared stub when the plane is disabled: one no-op call per use."""

    class _Ctx:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    _CTX = _Ctx()

    def begin(self):
        return self

    def phase(self, name: str):
        return self._CTX

    def record_phase(self, name: str, ms: float) -> None:
        pass

    def end(self, tokens: Optional[int] = None):
        return None

    def _finalize_open_step(self) -> None:
        pass


NOOP_CLOCK = _NoopClock()

_runs_lock = threading.Lock()
_runs: Dict[int, _RunContext] = {}       # thread id -> run context


def start_run(run_id: Optional[str] = None, *, group: str = "",
              rank: int = 0, world: int = 1,
              flops_per_token: float = 0.0, peak_flops: float = 0.0,
              tokens_per_step: int = 0,
              sink: Optional[Callable[[List[dict]], Any]] = None,
              meta: Optional[dict] = None) -> Optional[_RunContext]:
    """Open a training-run context on this thread (TrainWorker installs
    one around the user loop; bench.py opens its own).  ``sink`` takes
    report batches (``report_step_stats`` payload); None = local-only
    (the ledger still accumulates).  Returns None when disabled."""
    # raylint: disable=kill-switch -- once per training RUN, not per step; disabled runs get the shared no-op clock
    if not enabled():
        return None
    run = _RunContext(run_id or f"run-{uuid.uuid4().hex[:8]}",
                      group=group, rank=rank, world=world,
                      flops_per_token=flops_per_token,
                      peak_flops=peak_flops,
                      tokens_per_step=tokens_per_step, sink=sink,
                      meta=meta)
    with _runs_lock:
        _runs[threading.get_ident()] = run
    return run


def end_run(run: Optional[_RunContext] = None) -> Optional[dict]:
    """Close the thread's run (or the given one): finalizes the ledger,
    flushes reports, pushes the summary to the GCS.  Returns the
    summary dict (None when no run was active)."""
    with _runs_lock:
        if run is None:
            run = _runs.pop(threading.get_ident(), None)
        else:
            for tid, r in list(_runs.items()):
                if r is run:
                    _runs.pop(tid, None)
    if run is None:
        return None
    return run.close()


def current_run() -> Optional[_RunContext]:
    """The thread's run context, falling back (like air.session) to the
    process's single run so user helper threads resolve it too."""
    with _runs_lock:
        run = _runs.get(threading.get_ident())
        if run is None and len(_runs) == 1:
            run = next(iter(_runs.values()))
        return run


def step_clock():
    """The active run's StepClock (the no-op stub when the plane is
    disabled or no run is open) — the one import a train loop needs:

    >>> clock = step_clock()
    >>> for batch in data:            # doctest: +SKIP
    ...     clock.begin()
    ...     with clock.phase("host_dispatch"):
    ...         state, metrics = step_fn(state, batch)
    ...     with clock.phase("device_compute"):
    ...         jax.block_until_ready(metrics)
    ...     clock.end(tokens=batch_tokens)
    """
    run = current_run()
    if run is None:
        return NOOP_CLOCK
    return run.clock


def set_model_info(*, flops_per_token: Optional[float] = None,
                   peak_flops: Optional[float] = None,
                   tokens_per_step: Optional[int] = None) -> None:
    """Teach the active run its model arithmetic (from inside the train
    loop — the framework can't derive FLOPs/token generically): with
    these set the goodput ledger reports MFU, not just time buckets."""
    run = current_run()
    if run is None:
        return
    if flops_per_token is not None:
        run.ledger.flops_per_token = float(flops_per_token)
    if peak_flops is not None:
        run.ledger.peak_flops = float(peak_flops)
    if tokens_per_step is not None:
        run.tokens_per_step = int(tokens_per_step)


def record_phase(name: str, ms: float) -> None:
    """Attribute ``ms`` to phase ``name`` of the active step (the hook
    ``sync_gradients`` / ``session.report`` use); outside a step it
    lands in the run ledger's out-of-step totals."""
    run = current_run()
    if run is not None:
        run.clock.record_phase(name, ms)


def instrument_step(step_fn: Callable, *,
                    tokens_per_step: Optional[int] = None) -> Callable:
    """Wrap a jitted train step so each call is one clocked step:
    ``begin`` -> dispatch as ``host_dispatch`` -> ``block_until_ready``
    fence as ``device_compute`` -> ``end``.  The fence serializes the
    device pipeline — use the explicit :func:`step_clock` API in
    throughput-critical loops and fence only where bench.py does."""
    def timed(*args, **kwargs):
        clock = step_clock()
        clock.begin()
        with clock.phase("host_dispatch"):
            out = step_fn(*args, **kwargs)
        with clock.phase("device_compute"):
            try:
                import jax
                jax.block_until_ready(out)
            except Exception:
                pass
        clock.end(tokens=tokens_per_step)
        return out

    timed.__name__ = getattr(step_fn, "__name__", "train_step")
    return timed


# ----------------------------------------------------- GCS aggregation
def _median_mad(values: List[float]) -> Tuple[float, float]:
    med = statistics.median(values)
    mad = statistics.median([abs(v - med) for v in values])
    return med, mad


class GcsStepStatsTable:
    """GCS-side per-run step table + straggler detector + ledger store.

    Retention is bounded twice, like the cluster event table: at most
    ``gcs_max_step_runs`` runs (oldest-touched evicted first) each
    keeping the last ``gcs_step_stats_max_steps`` steps.  Ephemeral —
    never WALed, like task events and metrics.

    Straggler detection runs when every rank of a step has reported
    (``world`` from the reports) and the gang has >= 3 ranks — robust
    location/scale (median + k * MAD) needs a majority of honest
    ranks, and a 2-rank gang's median sits exactly between the ranks,
    so neither side can overshoot it meaningfully.  A rank whose step
    time exceeds ``median + straggler_mad_k * MAD`` by at least
    ``straggler_min_ms`` flips to straggling and emits ONE
    ``TRAIN_STRAGGLER`` event naming the phase with the largest
    overshoot vs the phase median; the state edge-triggers — it must
    recover (a clean analyzed step) before it can fire again."""

    def __init__(self, emit: Optional[Callable[..., Any]] = None,
                 max_runs: Optional[int] = None,
                 max_steps: Optional[int] = None):
        self._emit = emit
        self.max_runs = max_runs or CONFIG.gcs_max_step_runs
        self.max_steps = max_steps or CONFIG.gcs_step_stats_max_steps
        self._runs: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self._stragglers_total = 0

    def _run_entry(self, run_id: str, group: str, world: int) -> dict:
        entry = self._runs.get(run_id)
        if entry is None:
            entry = {"run": run_id, "group": group, "world": world,
                     "ranks": {}, "steps": OrderedDict(),
                     "order": deque(), "straggling": {},
                     "summaries": {}, "ts_start": time.time(),
                     "last_ts": time.time(), "nsteps_seen": 0,
                     "skew": deque(maxlen=64)}
            self._runs[run_id] = entry
            while len(self._runs) > self.max_runs:
                self._runs.popitem(last=False)
        entry["group"] = group or entry["group"]
        entry["world"] = max(world, entry["world"])
        self._runs.move_to_end(run_id)
        return entry

    def put(self, reports: List[dict]) -> int:
        """Merge one batch of rank reports; returns steps rotated out.
        A report carrying ``summary`` stores the rank's goodput ledger
        instead of a step."""
        dropped = 0
        analyze: List[Tuple[dict, int]] = []
        with self._lock:
            for rep in reports:
                if not isinstance(rep, dict) or not rep.get("run"):
                    continue
                entry = self._run_entry(rep["run"],
                                        rep.get("group", ""),
                                        int(rep.get("world", 1)))
                entry["last_ts"] = time.time()
                rank = int(rep.get("rank", 0))
                meta = rep.get("meta")
                if meta is not None:
                    entry["ranks"][rank] = dict(meta, rank=rank)
                if "summary" in rep:
                    entry["summaries"][rank] = rep["summary"]
                    continue
                if "step" not in rep:
                    continue
                step = int(rep["step"])
                srec = entry["steps"].get(step)
                if srec is None:
                    srec = entry["steps"][step] = {}
                    entry["order"].append(step)
                    entry["nsteps_seen"] += 1
                    while len(entry["steps"]) > self.max_steps:
                        victim = entry["order"].popleft()
                        entry["steps"].pop(victim, None)
                        dropped += 1
                srec[rank] = {"step_ms": float(rep.get("step_ms", 0.0)),
                              "ts": rep.get("ts"),
                              "phases": dict(rep.get("phases") or {})}
                if len(srec) >= entry["world"] and \
                        not srec.get("_analyzed"):
                    srec["_analyzed"] = True
                    analyze.append((entry, step))
        for entry, step in analyze:
            self._analyze_step(entry, step)
        return dropped

    # -- straggler detection ----------------------------------------------
    def _analyze_step(self, entry: dict, step: int) -> None:
        with self._lock:
            srec = entry["steps"].get(step)
            if srec is None:
                return
            ranks = {r: v for r, v in srec.items()
                     if isinstance(r, int)}
        if len(ranks) < 2:
            return
        totals = {r: v["step_ms"] for r, v in ranks.items()}
        med, mad = _median_mad(list(totals.values()))
        k = CONFIG.straggler_mad_k
        floor = CONFIG.straggler_min_ms
        skew = max(totals.values()) - med
        with self._lock:
            entry["skew"].append({"step": step, "median_ms": round(med, 3),
                                  "max_ms": round(max(totals.values()), 3),
                                  "skew_ms": round(skew, 3)})
        if len(ranks) < 3:
            # a 2-rank gang's median sits exactly between the ranks —
            # skew is recorded, but median+MAD can't name a straggler
            return
        for rank, total in totals.items():
            overshoot = total - (med + k * mad)
            straggling = overshoot >= floor
            with self._lock:
                was = entry["straggling"].get(rank, False)
                entry["straggling"][rank] = straggling
            if straggling and not was:
                phase = self._slowest_phase(ranks, rank)
                self._stragglers_total += 1
                if self._emit is not None:
                    try:
                        self._emit(
                            "WARNING", "step_stats", "TRAIN_STRAGGLER",
                            f"run {entry['run']} rank {rank} straggling "
                            f"at step {step}: {total:.1f}ms vs median "
                            f"{med:.1f}ms (slowest phase: {phase})",
                            run=entry["run"], group=entry["group"],
                            rank=rank, step=step, phase=phase,
                            step_ms=round(total, 3),
                            median_ms=round(med, 3),
                            overshoot_ms=round(total - med, 3),
                            worker_id=(entry["ranks"].get(rank) or {}
                                       ).get("worker_id"),
                            node_id=(entry["ranks"].get(rank) or {}
                                     ).get("node_id"))
                    except Exception:
                        pass

    @staticmethod
    def _slowest_phase(ranks: Dict[int, dict], rank: int) -> str:
        """The phase where ``rank`` overshoots the cross-rank phase
        median the most — the slice that names the bottleneck."""
        mine = ranks[rank].get("phases") or {}
        worst, worst_over = "", float("-inf")
        for name, ms in mine.items():
            peers = [v.get("phases", {}).get(name, 0.0)
                     for r, v in ranks.items() if r != rank]
            med = statistics.median(peers) if peers else 0.0
            over = ms - med
            if over > worst_over:
                worst, worst_over = name, over
        return worst or "step"

    # -- queries -----------------------------------------------------------
    def list_runs(self, run: Optional[str] = None,
                  limit: int = 100) -> List[dict]:
        with self._lock:
            out = []
            for run_id, entry in self._runs.items():
                if run and not (run_id.startswith(run)
                                or entry["group"].startswith(run)):
                    continue
                out.append({
                    "run": run_id, "group": entry["group"],
                    "world": entry["world"],
                    "ranks": {r: dict(m)
                              for r, m in entry["ranks"].items()},
                    "steps_seen": entry["nsteps_seen"],
                    "steps_retained": len(entry["steps"]),
                    "ts_start": entry["ts_start"],
                    "last_ts": entry["last_ts"],
                    "straggling": {r: s for r, s
                                   in entry["straggling"].items() if s},
                    "skew": list(entry["skew"]),
                })
            return out[-max(0, int(limit)):]

    def steps(self, run: str, limit: int = 64) -> List[dict]:
        with self._lock:
            entry = self._runs.get(run)
            if entry is None:
                for rid, e in self._runs.items():
                    if rid.startswith(run) or e["group"].startswith(run):
                        entry = e
                        break
            if entry is None:
                return []
            out = []
            for step in list(entry["order"])[-max(0, int(limit)):]:
                srec = entry["steps"].get(step)
                if srec is None:
                    continue
                out.append({"step": step,
                            "ranks": {r: dict(v) for r, v in srec.items()
                                      if isinstance(r, int)}})
            return out

    def summary(self, run: Optional[str] = None) -> Optional[dict]:
        """The goodput ledger view of one run (latest by default):
        per-rank summaries plus an aggregate."""
        with self._lock:
            entry = None
            if run:
                entry = self._runs.get(run)
                if entry is None:
                    for rid, e in self._runs.items():
                        if rid.startswith(run) \
                                or e["group"].startswith(run):
                            entry = e
                            break
            elif self._runs:
                # latest run with any summary, else latest touched
                for e in reversed(self._runs.values()):
                    if e["summaries"]:
                        entry = e
                        break
                if entry is None:
                    entry = next(reversed(self._runs.values()))
            if entry is None:
                return None
            summaries = {r: dict(s)
                         for r, s in sorted(entry["summaries"].items())}
            out = {"run": entry["run"], "group": entry["group"],
                   "world": entry["world"], "ranks": summaries,
                   "steps_seen": entry["nsteps_seen"],
                   "skew": list(entry["skew"])}
        if summaries:
            vals = list(summaries.values())
            out["aggregate"] = {
                "tokens": sum(s.get("tokens", 0) for s in vals),
                "steps": max(s.get("steps", 0) for s in vals),
                "goodput": round(sum(s.get("goodput", 0.0)
                                     for s in vals) / len(vals), 4),
                "mfu": round(sum(s.get("mfu", 0.0)
                                 for s in vals) / len(vals), 4),
                "tokens_per_s": round(sum(s.get("tokens_per_s", 0.0)
                                          for s in vals), 1),
            }
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"runs": len(self._runs),
                    "steps_retained": sum(len(e["steps"])
                                          for e in self._runs.values()),
                    "stragglers_total": self._stragglers_total,
                    "max_runs": self.max_runs,
                    "max_steps": self.max_steps}


# -------------------------------------------------- profile trace merge
def step_trace_events(task_rows: List[dict],
                      window: Optional[Tuple[float, float]] = None
                      ) -> List[dict]:
    """STEP records from the GCS task table -> chrome-trace slices
    (the ``ray-tpu profile --group`` correlation rows).  ``window``
    (wall-clock start, end) filters to the capture span."""
    events: List[dict] = []
    for t in task_rows:
        for ev in t.get("events") or []:
            if ev.get("state") != "STEP":
                continue
            dur_s = float(ev.get("dur_ms", 0.0)) / 1e3
            t_end = ev.get("ts", 0.0)
            if window and (t_end < window[0] or t_end - dur_s > window[1]):
                continue
            args = {"step": ev.get("step")}
            if ev.get("trace_id"):
                args["trace_id"] = ev["trace_id"]
            args.update({k: v for k, v in (ev.get("phases") or {}).items()})
            events.append({
                "name": f"step {ev.get('step', '?')}",
                "cat": "train_step", "ph": "X",
                "ts": (t_end - dur_s) * 1e6, "dur": dur_s * 1e6,
                "pid": _rank_pid(t.get("task_id", "")),
                "tid": "steps", "args": args,
            })
    return events


def _rank_pid(task_id: str) -> str:
    """``step-<run>-r<rank>`` -> ``rank <rank>`` (the merged profile
    trace keys rows by rank, so step slices land on the rank's row)."""
    if "-r" in task_id:
        tail = task_id.rsplit("-r", 1)[1]
        if tail.isdigit():
            return f"rank {int(tail)}"
    return task_id[:16]


def merged_profile_trace(per_rank: Dict[int, Dict[str, int]],
                         interval_s: float, t_start: float,
                         step_events: Optional[List[dict]] = None
                         ) -> List[dict]:
    """Fold per-rank ``profile`` captures into one Perfetto trace.

    Each rank becomes a ``pid`` row (``rank N``); its folded stacks lay
    out as time-weighted complete slices (count x sampling interval) in
    hotness order from the capture's real start time — sample placement
    WITHIN the window is synthetic (a sampling profile has no
    ordering), but the window bounds are wall-clock, so the rows line
    up against each other and against the STEP slices passed in
    ``step_events`` (state.api timeline shape)."""
    from ray_tpu._private import profiler
    events: List[dict] = []
    for rank in sorted(per_rank):
        counts, leaf_lines = profiler.split_leaf_detail(per_rank[rank])
        t = t_start * 1e6
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1]):
            dur = n * interval_s * 1e6
            leaf = stack.rsplit(";", 1)[-1]
            args = {"stack": stack.replace(";", "\n"), "samples": n}
            lines = (leaf_lines or {}).get(leaf)
            if lines:
                hot = max(lines.items(), key=lambda kv: kv[1])
                args["top_line"] = hot[0]
            events.append({
                "name": leaf, "cat": "profile", "ph": "X",
                "ts": t, "dur": dur,
                "pid": f"rank {rank}", "tid": "samples",
                "args": args,
            })
            t += dur
    for ev in step_events or []:
        events.append(ev)
    return events
