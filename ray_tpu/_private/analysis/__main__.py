from ray_tpu._private.analysis.cli import main

if __name__ == "__main__":
    main()
