"""Debug-mode lock-order sanitizer (``RAY_TPU_DEBUG_LOCKS=1``).

Lock-order inversions (thread 1 holds A wanting B, thread 2 holds B
wanting A) only deadlock when the two acquisition windows actually
overlap — which makes them the canonical one-in-a-thousand bug: the
raylet ``_kill_worker`` TOCTOU shipped and survived chaos runs because
the colliding window was microseconds wide.  A lockdep-style sanitizer
removes the probability from the bug class: it records the ORDER in
which lock classes are acquired, and the first time any thread ever
acquires B while holding A after some thread acquired A while holding
B — overlapping or not — it raises with both acquisition sites.

Mechanism (a pure-Python cousin of the kernel's lockdep):

* ``install()`` monkeypatches ``threading.Lock``/``threading.RLock``
  with factories.  A lock constructed by an *instrumented module*
  (creation frame under ``ray_tpu/``, excluding this file) gets a
  wrapper; everything else (stdlib internals, user code, jax) gets the
  real primitive untouched.
* locks are classed by their CREATION SITE (``file:line``), like
  lockdep classes — the raylet's thousand per-connection locks form one
  class, so an inversion between two *instances* is caught the first
  time the pattern appears anywhere.  Same-class pairs (A1 vs A2 from
  one site) are deliberately NOT edges: hand-over-hand between
  same-class instances is a legitimate pattern and instance-level
  cycles on one class cannot be distinguished statically from it.
* each successful acquire appends to a ``threading.local`` held-stack
  and records ``held-class -> new-class`` edges into a process-global
  graph; a new edge triggers a DFS for a path back, and a cycle raises
  ``LockOrderError`` naming every edge's acquire site (file:line of
  both sides — the test contract).
* ``Condition.wait`` works unmodified: it releases/re-acquires through
  the wrapper (the RLock wrapper forwards ``_release_save`` /
  ``_acquire_restore`` / ``_is_owned`` so recursion counts survive the
  wait), so the held-stack stays truthful across waits.

The wrappers add roughly a guarded list append per acquire on
instrumented locks — debug-mode cost, which is why this is an opt-in
sanitizer wired into the chaos and compiled-DAG suites rather than an
always-on layer.  Cross-thread release of a plain Lock (the
completion-gate pattern, legal for Lock) is handled: the release drops
the entry from the RECORDING thread's stack, so no phantom entries
haunt the acquirer.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.config import CONFIG

__all__ = ["LockOrderError", "install", "installed", "maybe_install",
           "enabled", "reset", "edges"]


class LockOrderError(RuntimeError):
    """A lock acquisition-order cycle: the chain names, for every edge,
    where the second lock was acquired while the first was held."""


def enabled() -> bool:
    """Debug gate: RAY_TPU_DEBUG_LOCKS env wins, then the config flag
    (declared as ``debug_locks``, so both spellings resolve here)."""
    return CONFIG.debug_locks


# creation-frame filename prefixes that get instrumented wrappers; the
# concurrency-heavy runtime core, not the whole world — wrapping every
# library lock would tax untargeted suites and drown the graph
_SELF = os.path.abspath(os.path.dirname(__file__))
_DEFAULT_PREFIXES = tuple(
    os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", p))
    for p in (
        os.path.join("_private", "rpc.py"),
        os.path.join("_private", "transfer.py"),
        "runtime",
        os.path.join("util", "collective"),
        os.path.join("dag", ""),
        os.path.join("experimental", "channel.py"),
        "serve",
    ))

_real_lock = threading.Lock
_real_rlock = threading.RLock
_installed = False
_prefixes: Tuple[str, ...] = _DEFAULT_PREFIXES

# acquisition-order graph over lock classes (creation sites):
# (a_site, b_site) -> (a_acquire_site, b_acquire_site) of the FIRST
# observation — kept so a later inverse edge can name both windows
_graph_lock = _real_lock()
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
_succ: Dict[str, Set[str]] = {}

# held stacks keyed by recording thread id (NOT threading.local): a
# plain Lock may legally be released by a different thread than its
# acquirer (completion-gate pattern), and the release must drop the
# entry from the RECORDING thread's stack or it haunts that thread as
# a phantom, spraying false order edges.  _held_guard serializes stack
# mutation; empty stacks are pruned so dead threads don't accumulate.
_held_guard = _real_lock()
_held_by_tid: Dict[int, List[Tuple[str, str, object]]] = {}


def _held_snapshot(tid: Optional[int] = None
                   ) -> List[Tuple[str, str, object]]:
    """Copy of a thread's held stack (default: the calling thread)."""
    t = tid if tid is not None else threading.get_ident()
    with _held_guard:
        return list(_held_by_tid.get(t, ()))


def _caller_site(depth: int = 2) -> str:
    f = sys._getframe(depth)
    while f is not None and os.path.abspath(
            f.f_code.co_filename).startswith(_SELF):
        f = f.f_back
    if f is None:  # pragma: no cover - only if called from this module
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> ... -> dst over _succ (graph lock held)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _succ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_edges(new_site: str, acquire_site: str) -> None:
    held = _held_snapshot()
    if not held:
        return
    for held_site, held_acq, _lock in held:
        if held_site == new_site:
            continue  # same class: hand-over-hand, not an order edge
        key = (held_site, new_site)
        with _graph_lock:
            if key in _edges:
                continue
            # would this edge close a cycle?  path new -> ... -> held
            path = _find_path(new_site, held_site)
            if path is None:
                _edges[key] = (held_acq, acquire_site)
                _succ.setdefault(held_site, set()).add(new_site)
                continue
            lines = [
                f"lock-order inversion: acquiring {new_site} (at "
                f"{acquire_site}) while holding {held_site} (acquired "
                f"at {held_acq}), but the inverse order is already on "
                f"record:"]
            for a, b in zip(path, path[1:]):
                ea = _edges.get((a, b))
                where = f" (at {ea[1]}, holding since {ea[0]})" \
                    if ea else ""
                lines.append(f"  {a} -> {b}{where}")
        raise LockOrderError("\n".join(lines))


def _on_acquired(site: str, lock: object, first: bool) -> None:
    if not first:
        return  # RLock recursion: already on the stack
    acq = _caller_site(3)
    _record_edges(site, acq)
    tid = threading.get_ident()
    lock._held_tid = tid
    with _held_guard:
        _held_by_tid.setdefault(tid, []).append((site, acq, lock))


def _on_released(lock: object) -> None:
    # drop the entry from the stack of the thread that RECORDED it —
    # which, for a plain Lock handed across threads, may not be the
    # releasing thread
    tid = getattr(lock, "_held_tid", None)
    if tid is None:
        return
    with _held_guard:
        held = _held_by_tid.get(tid)
        if held is None:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][2] is lock:
                del held[i]
                break
        if not held:
            del _held_by_tid[tid]


class _DebugLock:
    """threading.Lock wrapper recording acquisition order."""

    __slots__ = ("_lock", "_site", "_held_tid")

    def __init__(self, site: str):
        self._lock = _real_lock()
        self._site = site
        self._held_tid: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            try:
                _on_acquired(self._site, self, True)
            except LockOrderError:
                # report the inversion WITHOUT converting it into the
                # very hang it diagnoses: a caller that survives the
                # exception must not leave the lock held forever
                self._lock.release()
                raise
        return ok

    def release(self) -> None:
        self._lock.release()
        _on_released(self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self._site} {self._lock!r}>"


class _DebugRLock:
    """threading.RLock wrapper; forwards the Condition protocol so
    ``Condition.wait`` saves/restores recursion counts correctly."""

    __slots__ = ("_lock", "_site", "_count", "_held_tid")

    def __init__(self, site: str):
        self._lock = _real_rlock()
        self._site = site
        self._held_tid: Optional[int] = None
        self._count = 0  # this-thread recursion depth is what matters;
        # cross-thread reads of the int are benign (only the owner
        # mutates it between acquire/release pairs)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._count += 1
            try:
                _on_acquired(self._site, self, self._count == 1)
            except LockOrderError:
                self._count -= 1
                self._lock.release()
                raise
        return ok

    def release(self) -> None:
        # bookkeeping BEFORE the inner release: once the real lock is
        # free another thread may acquire the wrapper immediately, and
        # a late decrement here would corrupt the shared count (phantom
        # held-stack entries -> bogus order edges).  Only the owner may
        # legitimately release, so mutating first is safe; a non-owner
        # falls through to the inner release's RuntimeError untouched.
        if self._count > 0 and self._lock._is_owned():
            self._count -= 1
            if self._count == 0:
                _on_released(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # --- Condition protocol (delegate; keep held-stack truthful) ----
    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        # same ordering rule as release(): zero the shared count before
        # the inner lock actually frees, or a waiter's first acquire
        # races the stale count
        saved = self._count
        self._count = 0
        _on_released(self)
        state = self._lock._release_save()
        return (state, saved)

    def _acquire_restore(self, state) -> None:
        inner, saved = state
        self._lock._acquire_restore(inner)
        self._count = saved
        _on_acquired(self._site, self, True)

    def __repr__(self) -> str:
        return f"<DebugRLock {self._site} {self._lock!r}>"


def _should_wrap(filename: str) -> bool:
    path = os.path.abspath(filename)
    if path.startswith(_SELF):
        return False
    return any(path.startswith(p) for p in _prefixes)


def _lock_factory():
    if enabled():
        f = sys._getframe(1)
        if _should_wrap(f.f_code.co_filename):
            return _DebugLock(f"{f.f_code.co_filename}:{f.f_lineno}")
    return _real_lock()


def _rlock_factory():
    if enabled():
        f = sys._getframe(1)
        if _should_wrap(f.f_code.co_filename):
            return _DebugRLock(f"{f.f_code.co_filename}:{f.f_lineno}")
    return _real_rlock()


def install(prefixes: Optional[Tuple[str, ...]] = None) -> None:
    """Patch ``threading.Lock``/``RLock`` with the gating factories.
    Idempotent; with the gate off the factories hand out real locks, so
    installing is cheap even when debugging is disabled (the tier-1
    chaos/compiled-DAG fixtures rely on that: install once, gate via
    env per suite)."""
    global _installed, _prefixes
    if prefixes:
        _prefixes = tuple(os.path.abspath(p) for p in prefixes)
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def installed() -> bool:
    return _installed


def maybe_install() -> None:
    """Install when the debug gate is on (called from ray_tpu.__init__
    so spawned daemons self-instrument off the inherited env)."""
    if enabled():
        install()


def reset() -> None:
    """Drop the recorded graph and held stacks (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _succ.clear()
    with _held_guard:
        _held_by_tid.clear()


def edges() -> Dict[Tuple[str, str], Tuple[str, str]]:
    with _graph_lock:
        return dict(_edges)
