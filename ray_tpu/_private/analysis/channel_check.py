"""Debug-mode shm-ring protocol checker (``RAY_TPU_DEBUG_CHANNELS=1``).

The ``experimental/channel.py`` rings are correct only under three
disciplines that nothing enforces at runtime:

* **single writer** — exactly one ``ChannelWriter`` instance ever
  publishes into a given ring (per-slot seq words have one writer);
* **seq-word-last** — the writer publishes payload, then ``len`` /
  ``flags``, then ``seq`` LAST, so a reader that observes ``seq ==
  k+1`` sees a complete item (x86-TSO store ordering);
* **cumulative in-order acks** — reader ``r`` publishes ``acks[r] =
  k+1`` exactly once per item, in consume order (acking ``k+1`` before
  ``k`` would release ``k``'s slot early).

A violation of any of these corrupts items *silently* — the consumer
deserializes garbage long after the racing write retired.  With the
debug gate on, ``channel.py`` calls the checks below on every publish
and ack and the FIRST protocol break raises ``ChannelProtocolError``
naming the slot and the expected/observed control words.

The writer claim rides the channel header's reserved word at offset 32:
each ``ChannelWriter`` instance mints a random nonzero 64-bit identity
and stamps it on first publish; a different instance (same process or
not — the header is shared memory) publishing later trips the check.
"""

from __future__ import annotations

import os
import random
import struct

from ray_tpu._private.config import CONFIG

# module-local RNG reseeded after fork (the tracing_helper idiom):
# zygote-forked prefork workers share the global MT state, and two
# forked writers minting IDENTICAL ids would make the single-writer
# claim check vacuously pass for the exact cross-process bug it exists
# to catch.  The pid is mixed in as a belt-and-suspenders layer.
_id_rng = random.Random()
if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_id_rng.seed)

_U64 = struct.Struct("<Q")
# header offset of the debug writer-claim word (reserved range 32..64
# in the channel layout; zero = unclaimed, matching create()'s zeroing)
CLAIM_OFF = 32

__all__ = ["ChannelProtocolError", "enabled", "writer_id",
           "check_publish", "check_ack"]


class ChannelProtocolError(RuntimeError):
    """Single-writer / seq-word / ack discipline violated on a ring."""


def enabled() -> bool:
    """Debug gate: RAY_TPU_DEBUG_CHANNELS env wins via the config
    resolution of the ``debug_channels`` flag."""
    return CONFIG.debug_channels


def writer_id() -> int:
    """A fresh nonzero 64-bit writer identity (per ChannelWriter)."""
    return ((_id_rng.getrandbits(63) ^ (os.getpid() << 32)) & (2**63 - 1)) | 1


def check_publish(ch, k: int, wid: int) -> None:
    """Before writer publishes item ``k``: claim the ring and verify
    the slot's previous tenant is exactly the item the protocol says it
    must be."""
    view = ch._view
    claimed = _U64.unpack_from(view, CLAIM_OFF)[0]
    if claimed == 0:
        _U64.pack_into(view, CLAIM_OFF, wid)
    elif claimed != wid:
        raise ChannelProtocolError(
            f"channel {ch.oid.hex()[:12]}: second writer (claim word "
            f"{claimed:#x} != this writer {wid:#x}) — a ring has "
            f"exactly ONE ChannelWriter for its lifetime")
    off = ch._slot_off(k)
    seq = _U64.unpack_from(view, off)[0]
    expect = k + 1 - ch.nslots if k >= ch.nslots else 0
    if seq != expect:
        raise ChannelProtocolError(
            f"channel {ch.oid.hex()[:12]} slot {k % ch.nslots}: seq "
            f"word is {seq} before publishing item {k} (expected "
            f"{expect}) — concurrent writer or seq reuse")


def check_read(ch, k: int, size: int) -> None:
    """When a reader observes ``seq == k+1``: a len word past the slot
    capacity means the writer stamped seq before the payload metadata
    (seq-word-last violated) or the write tore."""
    if size > ch.capacity:
        raise ChannelProtocolError(
            f"channel {ch.oid.hex()[:12]} slot {k % ch.nslots}: len "
            f"{size} exceeds capacity {ch.capacity} under seq {k + 1} "
            f"— seq published before len (seq-word-last violated) or "
            f"torn write")


def check_ack(ch, idx: int, want: int) -> None:
    """Before reader ``idx`` publishes ``acks[idx] = want``: the
    previous value must be exactly ``want - 1`` (cumulative, in-order,
    exactly-once)."""
    cur = _U64.unpack_from(ch._view, ch._acks_off + 8 * idx)[0]
    if cur != want - 1:
        raise ChannelProtocolError(
            f"channel {ch.oid.hex()[:12]} reader {idx}: acking item "
            f"{want} but ack word is {cur} (expected {want - 1}) — "
            f"out-of-order or double ack")
