"""raylint: framework-invariant static analysis + runtime sanitizers.

The runtime's five data/observability planes are built on hand-rolled
concurrency primitives — inline fast-method dispatch (rpc.py), shm
single-writer rings (experimental/channel.py), ``Deferred`` replies,
flusher threads — and the same mechanical bug classes kept surfacing in
review: an inline RPC handler that blocks (the full-duplex ring
deadlock docs/collective.md describes), lock-order races
(raylet ``_kill_worker`` TOCTOU), ContextVars dropped across executor
hops (the http_proxy double-root bug).  This package enforces those
invariants by machine instead of reviewer memory:

* **Static side** — an AST-based checker framework (``core.py``) with a
  project-wide index + best-effort call graph (``callgraph.py``) and
  one module per rule under ``checkers/``.  ``ray-tpu lint`` (and the
  tier-1 gate ``tests/test_static_analysis.py``) runs every checker
  over the whole package; any unallowlisted violation fails the suite.
  Findings are suppressed only by an *inline justification comment*
  (``# raylint: disable=<rule> -- <why>``) or a baseline entry in
  ``allowlist.txt`` — both REQUIRE the justification text, and stale
  baseline entries are themselves violations.

* **Runtime side** — debug-mode sanitizers, off unless asked for:
  ``RAY_TPU_DEBUG_LOCKS=1`` (``lock_sanitizer.py``) swaps
  ``threading.Lock/RLock`` created by instrumented modules for wrappers
  that record the per-thread lock acquisition-order graph into a
  process-global table and raise at the FIRST A->B / B->A inversion
  (not at the one-in-a-thousand actual deadlock);
  ``RAY_TPU_DEBUG_CHANNELS=1`` (``channel_check.py``) asserts the shm
  ring protocol's single-writer / seq-word-last / cumulative-ack
  discipline on every publish and ack.  The chaos and compiled-DAG
  suites run with both enabled.

See docs/static_analysis.md for the checker catalog and workflows.
"""

from ray_tpu._private.analysis.core import (  # noqa: F401
    Violation, ProjectIndex, run_lint, all_checkers,
)
