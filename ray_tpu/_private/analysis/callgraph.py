"""Best-effort call resolution + blocking-primitive matching.

Resolution is deliberately conservative-but-useful:

* bare names resolve to same-module functions, then ``from``-imports;
* ``alias.attr`` resolves through the module's import table;
* ``self.attr`` resolves to a method of the enclosing class (single
  level — no MRO walk; this codebase barely uses inheritance in the
  runtime core);
* as a last resort, an attribute call whose name is defined EXACTLY
  once in the whole package resolves to that definition (``kv_put`` is
  only GcsClient's) — ambiguity means no edge, never a guessed one.

Unresolvable calls simply end the walk on that edge; that is the main
source of false negatives, which is the right failure mode for a gate
(silent pass beats noisy block).  False positives from the heuristic
edges are absorbed by the allowlist with a written justification.

Nested ``def``/``lambda`` bodies are NOT scanned as part of their
enclosing function: they almost always run on another thread or later
(callbacks, Thread targets), so charging their calls to the enclosing
frame would mis-attribute the blocking thread.
"""

from __future__ import annotations

import ast
import builtins
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu._private.analysis.core import ModuleInfo, ProjectIndex

# direct blocking primitives, keyed by how the call site names them.
# attribute names here are matched on ANY receiver — distinctive enough
# in this codebase (``.call`` is the sync RPC, ``.wait`` is
# Event/Condition/future wait); the walk's SAFE set and the allowlist
# carry the exceptions.
BLOCKING_ATTRS: Dict[str, str] = {
    "result": "Future.result() wait",
    "wait": "Event/Condition/future wait",
    "call": "synchronous RPC Connection.call",
    "recv": "socket recv",
    "recv_into": "socket recv_into",
    "accept": "socket accept",
    "sendall": "socket sendall",
    "sendmsg": "socket sendmsg",
    "communicate": "subprocess communicate",
    "check_output": "subprocess check_output",
    "check_call": "subprocess check_call",
}

# module-level functions that block, as (dotted module, name)
BLOCKING_FUNCS: Dict[Tuple[str, str], str] = {
    ("time", "sleep"): "time.sleep",
    ("subprocess", "run"): "subprocess.run",
    ("subprocess", "check_output"): "subprocess.check_output",
    ("subprocess", "check_call"): "subprocess.check_call",
    ("socket", "create_connection"): "socket.create_connection",
    ("ray_tpu", "get"): "ray_tpu.get",
    ("ray_tpu", "wait"): "ray_tpu.wait",
    ("ray_tpu.api", "get"): "ray_tpu.get",
    ("ray_tpu.api", "wait"): "ray_tpu.wait",
}


def body_calls(node) -> Iterable[ast.Call]:
    """Every Call in ``node``'s body (an AST node or a statement list),
    skipping nested def/lambda bodies (they run on other threads/later;
    see module docstring)."""
    stack = list(node) if isinstance(node, list) \
        else list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def callee_parts(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(receiver dotted-or-None, attr/name) of a call's callee."""
    f = call.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        parts = []
        v = f.value
        while isinstance(v, ast.Attribute):
            parts.append(v.attr)
            v = v.value
        if isinstance(v, ast.Name):
            parts.append(v.id)
            return ".".join(reversed(parts)), f.attr
        return "?", f.attr
    return None, None


class Target:
    """A resolved function: (module, qualname, def node)."""

    __slots__ = ("mod", "qual", "node")

    def __init__(self, mod: ModuleInfo, qual: str, node: ast.AST):
        self.mod = mod
        self.qual = qual
        self.node = node

    @property
    def key(self) -> Tuple[str, str]:
        return (self.mod.modname, self.qual)


def resolve_call(index: ProjectIndex, mod: ModuleInfo,
                 scope: Optional[str], call: ast.Call) -> List[Target]:
    """Resolve a call to package-internal function definitions."""
    recv, name = callee_parts(call)
    if name is None:
        return []
    out: List[Target] = []
    if recv is None:
        # bare name: same module, then from-imports
        if name in mod.functions:
            return [Target(mod, name, mod.functions[name])]
        fi = mod.from_imports.get(name)
        if fi:
            tmod = index.module(fi[0])
            if tmod and fi[1] in tmod.functions:
                return [Target(tmod, fi[1], tmod.functions[fi[1]])]
        return _unique_fallback(index, name)
    if recv == "self" and scope and "." in scope:
        cls = scope.split(".")[0]
        qual = f"{cls}.{name}"
        if qual in mod.functions:
            return [Target(mod, qual, mod.functions[qual])]
        return _unique_fallback(index, name)
    # alias.attr through the import tables; ``from pkg import mod as m``
    # binds a MODULE through from_imports, so both tables apply
    root = recv.split(".")[0]
    dotted = mod.imports.get(root)
    if dotted is None:
        fi = mod.from_imports.get(root)
        if fi and index.module(f"{fi[0]}.{fi[1]}") is not None:
            dotted = f"{fi[0]}.{fi[1]}"
    if dotted:
        full = ".".join([dotted] + recv.split(".")[1:])
        tmod = index.module(full)
        if tmod and name in tmod.functions:
            return [Target(tmod, name, tmod.functions[name])]
        return out
    return _unique_fallback(index, name)


# names the unique-definition fallback must never claim: builtins and
# ubiquitous method names resolve to stdlib/dict/str behavior far more
# often than to the one package function that happens to share the name
_FALLBACK_DENY = frozenset(dir(__builtins__)) | frozenset(
    dir(builtins)) | frozenset({
        "get", "set", "put", "add", "pop", "update", "close", "stop",
        "start", "run", "read", "write", "send", "keys", "values",
        "items", "copy", "clear", "append", "extend", "join", "split",
        "strip", "encode", "decode", "submit", "apply", "init", "reset",
        "step", "sample", "train", "save", "restore", "count", "index",
    })


def _unique_fallback(index: ProjectIndex, name: str) -> List[Target]:
    if name in _FALLBACK_DENY or name.startswith("__"):
        return []
    cands = index.func_index.get(name, [])
    if len(cands) == 1:
        mod, qual = cands[0]
        return [Target(mod, qual, mod.functions[qual])]
    return []


def match_blocking(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """Description if this call site IS a blocking primitive."""
    recv, name = callee_parts(call)
    if name is None:
        return None
    if recv is None:
        fi = mod.from_imports.get(name)
        if fi and (fi[0], fi[1]) in BLOCKING_FUNCS:
            return BLOCKING_FUNCS[(fi[0], fi[1])]
        return None
    root = recv.split(".")[0]
    dotted = mod.imports.get(root)
    if dotted is not None and recv == root:
        desc = BLOCKING_FUNCS.get((dotted, name))
        if desc:
            return desc
    if name in BLOCKING_ATTRS:
        return BLOCKING_ATTRS[name]
    return None


class BlockingHit:
    """One blocking call reached from a walk root."""

    __slots__ = ("chain", "desc", "mod", "line")

    def __init__(self, chain: List[str], desc: str, mod: ModuleInfo,
                 line: int):
        self.chain = chain        # ["handler", "helper", ...]
        self.desc = desc
        self.mod = mod            # module of the blocking call site
        self.line = line


def find_blocking(index: ProjectIndex, start: Target,
                  safe: Set[Tuple[str, str]],
                  is_blocking: Callable[[ModuleInfo, ast.Call],
                                        Optional[str]] = match_blocking,
                  max_depth: int = 6,
                  max_hits: int = 4) -> List[BlockingHit]:
    """DFS the call graph from ``start``; report blocking primitives
    reachable on the caller's thread.  ``safe`` entries
    ((modname, qualname)) are trusted sinks the walk never enters."""
    hits: List[BlockingHit] = []
    seen: Set[Tuple[str, str]] = {start.key}

    def walk(t: Target, chain: List[str], depth: int) -> None:
        if len(hits) >= max_hits:
            return
        for call in body_calls(t.node):
            desc = is_blocking(t.mod, call)
            if desc:
                hits.append(BlockingHit(
                    chain + [f"{desc}"], desc, t.mod, call.lineno))
                if len(hits) >= max_hits:
                    return
                continue
            if depth >= max_depth:
                continue
            for nxt in resolve_call(index, t.mod, t.qual, call):
                if nxt.key in seen or nxt.key in safe:
                    continue
                seen.add(nxt.key)
                walk(nxt, chain + [nxt.qual], depth + 1)

    walk(start, [start.qual], 0)
    return hits
