"""``ray-tpu lint``: run raylint over a package tree, exit nonzero on
unallowlisted violations (docs/static_analysis.md).

Fast enough to gate tier-1 (<10s over the whole package: AST parse +
table walks, no imports of the analyzed code), zero new CI plumbing —
``tests/test_static_analysis.py`` invokes the same entry point.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

from ray_tpu._private.analysis import core


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray-tpu lint",
        description="framework-invariant static analyzer (raylint)")
    p.add_argument("--root", default=None,
                   help="package directory to lint (default: the "
                        "installed ray_tpu package)")
    p.add_argument("--rule", action="append", dest="rules",
                   help="run only this rule (repeatable)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore allowlist.txt (show every finding)")
    p.add_argument("--baseline", default=core.DEFAULT_BASELINE,
                   help="baseline allowlist path")
    p.add_argument("--list-rules", action="store_true",
                   help="print the checker catalog and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def run(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for checker in core.all_checkers():
            print(f"{checker.RULE:24s} {checker.DESCRIPTION}")
        return 0
    t0 = time.monotonic()
    baseline = None if args.no_baseline else args.baseline
    root = args.root
    if root is None:
        import ray_tpu
        root = os.path.dirname(os.path.abspath(ray_tpu.__file__))
    violations = core.run_lint(root=root, baseline=baseline,
                               rules=args.rules)
    for v in violations:
        print(v.render())
    if not args.quiet:
        dt = time.monotonic() - t0
        print(f"raylint: {len(violations)} violation(s) in "
              f"{dt:.2f}s ({root})", file=sys.stderr)
    return 1 if violations else 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
