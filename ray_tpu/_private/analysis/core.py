"""raylint core: project index, checker protocol, allowlists, runner.

The analyzer is deliberately a *heuristic* AST tool, not a type checker:
call resolution is best-effort (see callgraph.py) and every rule accepts
that a finding may be a justified design decision.  What keeps that
honest is the suppression contract:

* an inline comment ``# raylint: disable=<rule>[,<rule>...] -- <why>``
  on the offending line (or the line above it) suppresses the finding —
  but ONLY with non-empty justification text after ``--``;
* a baseline entry in ``allowlist.txt`` (``<rule> <path>::<symbol> --
  <why>``) suppresses every finding with that key — same justification
  requirement, and entries that no longer match anything are reported
  as ``stale-allowlist`` so the baseline can only shrink.

Violation keys are ``rule path::symbol`` (no line numbers), so the
baseline survives unrelated edits to the file.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# rules the suppression machinery itself emits; they can never be
# suppressed (a baseline that allowlists its own staleness is no
# baseline at all)
META_RULES = frozenset({"stale-allowlist", "allowlist-format"})

_DISABLE_RE = re.compile(
    r"#\s*raylint:\s*disable=([\w,\-]+)(?:\s*--\s*(\S.*))?")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str          # checker id, e.g. "inline-handler-purity"
    path: str          # repo-relative, e.g. "ray_tpu/_private/rpc.py"
    line: int          # 1-based anchor line (display only; not in key)
    symbol: str        # dotted context, e.g. "Connection._read_loop"
    message: str

    @property
    def key(self) -> str:
        """Line-number-free identity used by the baseline allowlist."""
        return f"{self.rule} {self.path}::{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: "
                f"{self.message}")


class ModuleInfo:
    """One parsed module: AST plus the lookup tables checkers need."""

    def __init__(self, path: str, relpath: str, modname: str, source: str):
        self.path = path
        self.relpath = relpath
        self.modname = modname
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # qualname ("Class.method" / "func" / "outer.inner") -> def node
        self.functions: Dict[str, ast.AST] = {}
        # class name -> ClassDef
        self.classes: Dict[str, ast.ClassDef] = {}
        # local alias -> dotted module ("rtm" -> "ray_tpu._private....")
        self.imports: Dict[str, str] = {}
        # local name -> (dotted module, original name)
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        # (line, rule) suppressions: line -> {rule: reason-or-None}
        self.suppressions: Dict[int, Dict[str, Optional[str]]] = {}
        # single-pass node buckets (checkers iterate these instead of
        # re-walking the tree): (call, receiver-dotted, callee name)
        # triples, and Load-context attribute accesses
        self.calls: List[Tuple[ast.Call, Optional[str], Optional[str]]] = []
        self.attr_loads: List[ast.Attribute] = []
        self._index_defs()
        self._index_nodes()
        self._index_suppressions()

    # ------------------------------------------------------------ indexing
    def _index_defs(self) -> None:
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}" if prefix else child.name
                    self.functions[qual] = child
                    visit(child, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    if not prefix:
                        self.classes[child.name] = child
                    visit(child, f"{prefix}{child.name}.")
        visit(self.tree, "")

    def _index_nodes(self) -> None:
        from ray_tpu._private.analysis.callgraph import callee_parts
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                recv, name = callee_parts(node)
                self.calls.append((node, recv, name))
            elif isinstance(node, ast.Attribute):
                if isinstance(node.ctx, ast.Load):
                    self.attr_loads.append(node)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or
                                 alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        (node.module, alias.name)

    def _index_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(text)
            if m:
                reason = (m.group(2) or "").strip() or None
                per = self.suppressions.setdefault(i, {})
                for rule in m.group(1).split(","):
                    per[rule.strip()] = reason

    # ------------------------------------------------------------- queries
    def enclosing_function(self, line: int) -> Optional[str]:
        """Qualname of the innermost def spanning ``line``."""
        best, best_span = None, None
        for qual, node in self.functions.items():
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = qual, span
        return best


class ProjectIndex:
    """Every module of the package parsed once, shared by all checkers."""

    def __init__(self, root: str, package: str = "ray_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        # repo dir the relpaths are relative to (parent of the package)
        self.base = os.path.dirname(self.root)
        self.modules: Dict[str, ModuleInfo] = {}    # modname -> info
        self.by_relpath: Dict[str, ModuleInfo] = {}
        self.errors: List[Violation] = []
        self._load()
        # global function-name index: bare name -> [(ModuleInfo, qualname)]
        self.func_index: Dict[str, List[Tuple[ModuleInfo, str]]] = {}
        for mod in self.modules.values():
            for qual in mod.functions:
                self.func_index.setdefault(
                    qual.rsplit(".", 1)[-1], []).append((mod, qual))

    def _load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.base)
                modname = rel[:-3].replace(os.sep, ".")
                if modname.endswith(".__init__"):
                    modname = modname[:-len(".__init__")]
                try:
                    with open(path, encoding="utf-8") as f:
                        src = f.read()
                    info = ModuleInfo(path, rel, modname, src)
                except (SyntaxError, UnicodeDecodeError) as e:
                    self.errors.append(Violation(
                        "parse-error", rel, getattr(e, "lineno", 0) or 0,
                        "<module>", f"cannot parse: {e}"))
                    continue
                self.modules[modname] = info
                self.by_relpath[rel] = info

    def module(self, dotted: str) -> Optional[ModuleInfo]:
        return self.modules.get(dotted)

    def suppressed(self, v: Violation) -> Tuple[bool, bool]:
        """(is suppressed inline, has justification).  Checks the
        violation's line and the line above it (trailing comment vs a
        comment line of its own)."""
        mod = self.by_relpath.get(v.path)
        if mod is None:
            return False, False
        for line in (v.line, v.line - 1):
            per = mod.suppressions.get(line)
            if per and (v.rule in per or "all" in per):
                reason = per.get(v.rule, per.get("all"))
                return True, reason is not None
        return False, False


# ------------------------------------------------------------------ baseline
_BASELINE_RE = re.compile(
    r"^(?P<rule>[\w\-]+)\s+(?P<path>\S+?)::(?P<symbol>\S+)"
    r"(?:\s*--\s*(?P<reason>\S.*))?$")


def load_baseline(path: str) -> Tuple[Dict[str, str], List[Violation]]:
    """Parse allowlist.txt -> ({violation key: reason}, format errors)."""
    entries: Dict[str, str] = {}
    errors: List[Violation] = []
    if not os.path.exists(path):
        return entries, errors
    rel = os.path.basename(path)
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            m = _BASELINE_RE.match(line)
            if not m:
                errors.append(Violation(
                    "allowlist-format", rel, lineno, "<entry>",
                    f"unparseable baseline entry: {line!r}"))
                continue
            if m.group("rule") in META_RULES:
                errors.append(Violation(
                    "allowlist-format", rel, lineno, "<entry>",
                    f"meta rule {m.group('rule')!r} cannot be baselined"))
                continue
            reason = (m.group("reason") or "").strip()
            if not reason:
                errors.append(Violation(
                    "allowlist-format", rel, lineno, "<entry>",
                    "baseline entry has no `-- justification`: "
                    f"{line!r}"))
                continue
            key = (f"{m.group('rule')} {m.group('path')}"
                   f"::{m.group('symbol')}")
            entries[key] = reason
    return entries, errors


DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "allowlist.txt")


def all_checkers() -> list:
    """The registered checker modules (each: RULE, DESCRIPTION, check)."""
    from ray_tpu._private.analysis.checkers import (
        async_hygiene, config_knobs, executor_context, inline_handlers,
        killswitch)
    return [inline_handlers, async_hygiene, executor_context,
            config_knobs, killswitch]


def run_lint(root: Optional[str] = None,
             baseline: Optional[str] = DEFAULT_BASELINE,
             rules: Optional[Sequence[str]] = None,
             index: Optional[ProjectIndex] = None,
             ) -> List[Violation]:
    """Run every (selected) checker over the package rooted at ``root``
    and return the violations that survive inline + baseline
    suppression.  Stale/format problems in the suppression layer are
    returned as violations themselves."""
    if index is None:
        if root is None:
            import ray_tpu
            root = os.path.dirname(os.path.abspath(ray_tpu.__file__))
        index = ProjectIndex(root)
    raw: List[Violation] = list(index.errors)
    for checker in all_checkers():
        if rules and checker.RULE not in rules:
            continue
        raw.extend(checker.check(index))

    entries: Dict[str, str] = {}
    out: List[Violation] = []
    if baseline:
        entries, fmt_errors = load_baseline(baseline)
        out.extend(fmt_errors)
    used_keys = set()
    for v in raw:
        inline, justified = index.suppressed(v)
        if inline:
            if not justified and v.rule not in META_RULES:
                out.append(Violation(
                    "allowlist-format", v.path, v.line, v.symbol,
                    f"inline `raylint: disable={v.rule}` has no "
                    f"`-- justification`"))
            used_keys.add(v.key)
            continue
        if v.key in entries:
            used_keys.add(v.key)
            continue
        out.append(v)
    if baseline and not rules:
        # staleness is only meaningful against a FULL run: under
        # --rule filtering, other rules' baseline entries legitimately
        # match nothing this pass
        rel = os.path.basename(baseline)
        for key in sorted(set(entries) - used_keys):
            out.append(Violation(
                "stale-allowlist", rel, 0, key,
                f"baseline entry matches no current finding: {key}"))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out
