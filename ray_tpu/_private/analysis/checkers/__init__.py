"""raylint rules, one module per checker (docs/static_analysis.md).

Each checker module exposes ``RULE`` (the id used in disable comments
and the baseline), ``DESCRIPTION``, and ``check(index) ->
list[Violation]``.  Register new checkers in ``core.all_checkers``.
"""
