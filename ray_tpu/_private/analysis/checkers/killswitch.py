"""kill-switch: plane emission guards stay cheap; metric names unique.

The observability planes (telemetry / events / step stats / tracing /
metrics history) share one kill-switch idiom: an ``enabled()`` helper that reads the
``RAY_TPU_*`` env var and the CONFIG flag.  That read is an env lookup
plus a config-lock round trip — fine at binding/attach time, a real
cost on per-emission hot paths (and the exact regression the tracing
plane measured before caching).  The sanctioned hot-path shape is the
generation-keyed flag cache (``tracing_helper._flags``): cache the
verdict keyed on ``CONFIG.generation()`` so runtime overrides still
take effect while steady-state reads are one tuple compare.

Rule (a): any function that calls a plane's ``enabled()`` must itself
reference ``CONFIG.generation()`` (i.e. BE the flag cache).  Binding-
time callers (instrument factories, ``attach``/``configure``) carry an
inline justification instead — which doubles as documentation that the
call is intentionally resolution-time.

Rule (b): a runtime-metrics instrument name may be registered at
exactly one call site.  ``_register`` silently dedupes by name, so a
second registration returns the FIRST site's instrument — with the
second site's boundaries/tag keys silently ignored (two modules can
disagree about one family forever without an error).  Dynamic
(non-literal) names are skipped.  Names must carry the ``ray_tpu_``
prefix so the exposition namespace stays collision-free.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ray_tpu._private.analysis import callgraph as cg
from ray_tpu._private.analysis.core import ProjectIndex, Violation

RULE = "kill-switch"
DESCRIPTION = ("plane enabled() calls outside generation-keyed caches; "
               "duplicate or unprefixed metric registrations")

# kill-switch helper owners: module -> its enabled() qualname
_PLANES = {
    "ray_tpu._private.runtime_metrics": "enabled",
    "ray_tpu._private.cluster_events": "enabled",
    "ray_tpu._private.step_stats": "enabled",
    "ray_tpu._private.metrics_history": "enabled",
    "ray_tpu.util.tracing.tracing_helper": "enabled",
}

_REGISTRARS = {"counter", "gauge", "histogram", "histogram_family",
               "counter_family", "gauge_callback"}
_RTM_MODULE = "ray_tpu._private.runtime_metrics"


def _references_generation(func: ast.AST) -> bool:
    for n in ast.walk(func):
        if isinstance(n, ast.Attribute) and n.attr == "generation" \
                and isinstance(n.value, ast.Name) \
                and n.value.id == "CONFIG":
            return True
    return False


def _check_enabled_callers(index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for mod in index.modules.values():
        for call, _recv, name in mod.calls:
            if name != "enabled":
                continue
            qual = mod.enclosing_function(call.lineno)
            if qual is None:
                continue  # module-level: binding time by definition
            resolved = cg.resolve_call(index, mod, qual, call)
            plane = None
            for t in resolved:
                if _PLANES.get(t.mod.modname) == t.qual:
                    plane = t.mod.modname
                    break
            if plane is None:
                continue
            if _references_generation(mod.functions[qual]):
                continue  # this IS the generation-keyed cache
            out.append(Violation(
                RULE, mod.relpath, call.lineno, qual,
                f"{plane.rsplit('.', 1)[-1]}.enabled() called "
                f"outside a CONFIG.generation()-keyed flag cache "
                f"(env read + config lock per call; use the "
                f"_flags idiom, or justify a binding-time call "
                f"inline)"))
    return out


def _check_registrations(index: ProjectIndex) -> List[Violation]:
    # literal name -> [(relpath, line, symbol, registrar)]
    sites: Dict[str, List[Tuple[str, int, str, str]]] = {}
    out: List[Violation] = []
    for mod in index.modules.values():
        if mod.modname == _RTM_MODULE:
            continue  # the factories themselves
        for node, recv, name in mod.calls:
            if name not in _REGISTRARS:
                continue
            resolved = cg.resolve_call(index, mod, None, node)
            if not any(t.mod.modname == _RTM_MODULE for t in resolved):
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            mname = node.args[0].value
            sym = mod.enclosing_function(node.lineno) or "<module>"
            sites.setdefault(mname, []).append(
                (mod.relpath, node.lineno, sym, name))
            if not mname.startswith("ray_tpu_"):
                out.append(Violation(
                    RULE, mod.relpath, node.lineno, sym,
                    f"runtime metric {mname!r} lacks the ray_tpu_ "
                    f"prefix"))
    for mname, regs in sorted(sites.items()):
        if len(regs) <= 1:
            continue
        first = regs[0]
        for relpath, line, sym, registrar in regs[1:]:
            out.append(Violation(
                RULE, relpath, line, sym,
                f"metric {mname!r} registered more than once "
                f"(first at {first[0]}:{first[1]}); _register dedupes "
                f"by name, so this site's {registrar} arguments are "
                f"silently ignored — share the instrument instead"))
    return out


def check(index: ProjectIndex) -> List[Violation]:
    return _check_enabled_callers(index) + _check_registrations(index)
