"""inline-handler-purity: fast-method handlers must never block.

The RPC layer (docs/rpc_fastpath.md) runs registered ``fast_methods``
INLINE on the connection's reader thread.  A handler that blocks there
stops the reader — and on a full-duplex connection that is a deadlock
shape, not a slowdown: the blocked send's drain depends on the peer,
whose own reader may be blocked on us (the collective take-handler
deadlock documented in util/collective/transport.py).  The contract
(rpc.py module docstring) is: buffer + notify + enqueue frames only; no
socket waits, no ``Deferred``/future result waits, no sleeps, no sync
RPCs, no store fetches.

This checker finds every ``fast_methods=`` registration (name
iterables, ``FAST_METHODS`` class attrs, and predicate functions — any
string the predicate compares against its ``method`` parameter counts
as fast), maps each fast name to its handler (``_rpc_<name>``
convention, plus ``if method == "<name>"`` dispatch branches), and
walks the call graph from each handler looking for blocking primitives
on the reader thread.  The reply path itself (``Connection._send`` /
``Deferred.resolve``) is a sanctioned sink: enqueue-and-coalesce is the
design, and its flush semantics are owned by rpc.py, not the handler.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.analysis import callgraph as cg
from ray_tpu._private.analysis.core import (ModuleInfo, ProjectIndex,
                                            Violation)

RULE = "inline-handler-purity"
DESCRIPTION = ("functions registered via rpc fast_methods must not "
               "transitively block on the reader thread")

# the rpc reply machinery: enqueueing a frame (and opportunistically
# flushing the write queue) is the transport's own accepted tradeoff —
# the handler contract is about waits on OTHER threads' progress
SAFE_SINKS: Set[Tuple[str, str]] = {
    ("ray_tpu._private.rpc", "Connection._send"),
    ("ray_tpu._private.rpc", "Connection._respond"),
    ("ray_tpu._private.rpc", "Connection._flush"),
    ("ray_tpu._private.rpc", "Connection.push"),
    ("ray_tpu._private.rpc", "Connection.close"),
    ("ray_tpu._private.rpc", "Deferred.resolve"),
    ("ray_tpu._private.rpc", "Deferred.fail"),
    ("ray_tpu._private.rpc", "Deferred._finish"),
    ("ray_tpu._private.rpc", "Deferred._bind"),
    ("ray_tpu._private.rpc", "_run_cb"),
}


def _string_items(node: ast.AST, mod: ModuleInfo) -> List[str]:
    """String literals inside a set/tuple/list/frozenset(...) literal,
    following one level of Name indirection to a module/class assign."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set", "tuple", "list") \
            and node.args:
        node = node.args[0]
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    if isinstance(node, ast.Name) or (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        name = node.id if isinstance(node, ast.Name) else node.attr
        out: List[str] = []
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    tname = None
                    if isinstance(tgt, ast.Name):
                        tname = tgt.id
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        tname = tgt.attr
                    if tname == name:
                        out.extend(_string_items(n.value, mod))
        return out
    return []


def _predicate_fast_names(mod: ModuleInfo, func: ast.AST) -> List[str]:
    """Strings a fast-method predicate compares its first ('method')
    parameter against — conservatively, ALL of them count as fast."""
    args = getattr(func, "args", None)
    if args is None or not args.args:
        return []
    pname = args.args[0].arg
    names: List[str] = []
    for n in ast.walk(func):
        if isinstance(n, ast.Compare) and isinstance(n.left, ast.Name) \
                and n.left.id == pname:
            for comp in n.comparators:
                if isinstance(comp, ast.Constant) and \
                        isinstance(comp.value, str):
                    names.append(comp.value)
                elif isinstance(comp, (ast.Tuple, ast.Set, ast.List)):
                    names.extend(e.value for e in comp.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, str))
    return names


def _fast_registrations(mod: ModuleInfo) -> List[Tuple[str, int]]:
    """(fast method name, registration line) pairs in this module."""
    out: List[Tuple[str, int]] = []
    for node, _recv, _name in mod.calls:
        for kw in node.keywords:
            if kw.arg != "fast_methods":
                continue
            val = kw.value
            names = _string_items(val, mod)
            if not names and isinstance(val, ast.Name):
                # predicate function — often nested inside the
                # registering __init__ (worker_main's shape), so fall
                # back to a unique leaf-name match
                fn = mod.functions.get(val.id)
                if fn is None:
                    cands = [f for q, f in mod.functions.items()
                             if q.rsplit(".", 1)[-1] == val.id]
                    fn = cands[0] if len(cands) == 1 else None
                if fn is not None:
                    names = _predicate_fast_names(mod, fn)
            for name in names:
                out.append((name, node.lineno))
    return out


def _branch_callees(mod: ModuleInfo,
                    fast_name: str) -> Tuple[bool, List[str]]:
    """(dispatch branch exists, functions called inside it) for
    ``if method == "<fast_name>"`` branches of any same-module
    dispatcher (a function with a ``method`` param).  A branch with no
    self-owned calls is a resolved, trivially pure handler."""
    found = False
    out: List[str] = []
    for qual, func in mod.functions.items():
        args = getattr(func, "args", None)
        if args is None or not any(a.arg == "method" for a in args.args):
            continue
        for n in ast.walk(func):
            if not isinstance(n, ast.If):
                continue
            if not _test_matches(n.test, "method", fast_name):
                continue
            found = True
            for call in cg.body_calls(n.body):
                recv, cname = cg.callee_parts(call)
                # only self-owned calls: a call on a parameter (e.g.
                # ``p.get(...)`` on the payload dict) is not the handler
                if cname and (recv is None or recv == "self"
                              or recv.startswith("self.")):
                    out.append(cname)
    return found, out


def _test_matches(test: ast.AST, param: str, value: str) -> bool:
    if isinstance(test, ast.Compare) and isinstance(test.left, ast.Name) \
            and test.left.id == param:
        for comp in test.comparators:
            if isinstance(comp, ast.Constant) and comp.value == value:
                return True
            if isinstance(comp, (ast.Tuple, ast.Set, ast.List)) and any(
                    isinstance(e, ast.Constant) and e.value == value
                    for e in comp.elts):
                return True
    if isinstance(test, ast.BoolOp):
        return any(_test_matches(v, param, value) for v in test.values)
    return False


def _handlers_for(index: ProjectIndex, mod: ModuleInfo,
                  fast_name: str) -> Tuple[bool, List[cg.Target]]:
    """(resolved, handler definitions) for a fast method name in its
    registering module."""
    targets: Dict[Tuple[str, str], cg.Target] = {}
    want = f"_rpc_{fast_name}"
    for qual, node in mod.functions.items():
        if qual.rsplit(".", 1)[-1] == want:
            t = cg.Target(mod, qual, node)
            targets[t.key] = t
    branch_found, callees = _branch_callees(mod, fast_name)
    for cname in callees:
        base = cname[5:] if cname.startswith("_rpc_") else cname
        for qual, node in mod.functions.items():
            leaf = qual.rsplit(".", 1)[-1]
            if leaf != cname and leaf != f"_rpc_{base}":
                continue
            args = getattr(node, "args", None)
            if args is not None and any(a.arg == "method"
                                        for a in args.args):
                # the callee is itself a dispatcher (worker_main's
                # dispatch closure forwards to the generic _handle):
                # its per-name branch is already scanned above, and
                # walking the WHOLE function would charge every other
                # method's branch to this fast name
                continue
            t = cg.Target(mod, qual, node)
            targets[t.key] = t
    return bool(targets) or branch_found, list(targets.values())


def check(index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for mod in index.modules.values():
        regs = _fast_registrations(mod)
        if not regs:
            continue
        seen_names = set()
        for fast_name, reg_line in regs:
            if fast_name in seen_names:
                continue
            seen_names.add(fast_name)
            resolved, handlers = _handlers_for(index, mod, fast_name)
            if not resolved:
                out.append(Violation(
                    RULE, mod.relpath, reg_line, f"fast:{fast_name}",
                    f"fast method {fast_name!r}: no handler definition "
                    f"resolved in {mod.relpath} (rename to "
                    f"_rpc_{fast_name} or dispatch via `if method == "
                    f"...` so the purity walk can see it)"))
                continue
            for h in handlers:
                for hit in cg.find_blocking(index, h, SAFE_SINKS):
                    out.append(Violation(
                        RULE, mod.relpath, hit.line
                        if hit.mod is mod else h.node.lineno,
                        h.qual,
                        f"fast method {fast_name!r} handler may block "
                        f"on the reader thread: "
                        f"{' -> '.join(hit.chain)} "
                        f"({hit.mod.relpath}:{hit.line})"))
    return out
