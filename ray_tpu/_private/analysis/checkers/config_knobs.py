"""config-knob: every CONFIG read resolves; every declared knob is read.

``Config.__getattr__`` raises ``AttributeError`` for undeclared names —
but only when the line actually runs, so a typo'd ``CONFIG.hartbeat_ms``
on a failure path ships silently and detonates in production.  The
reverse rot is dead knobs: a ``_declare`` whose every reader was
refactored away keeps masquerading as a tuning surface (and keeps its
``RAY_TPU_<NAME>`` env contract) while doing nothing.

Reads counted: ``CONFIG.<name>`` attribute access anywhere in the
package, ``getattr(CONFIG, "<literal>")``, and membership of the name
in a module-level tuple/set that is itself iterated against CONFIG (the
``_SCALED_FLAGS`` pattern lives inside config.py and is exempt anyway).
``CONFIG.set(...)/update(...)`` are writes, not reads — a knob that is
only ever written is still dead.

Dead knobs consumed only by tests keep their declaration honest with an
inline ``# raylint: disable=config-knob -- <why>`` on the _declare.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ray_tpu._private.analysis.core import ProjectIndex, Violation

RULE = "config-knob"
DESCRIPTION = ("CONFIG reads must resolve to a declared knob; declared "
               "knobs must be read somewhere")

_CONFIG_METHODS = {"set", "update", "generation", "snapshot",
                   "copy_overrides", "set_overrides",
                   "overrides_env_blob"}
_CONFIG_MODULE = "ray_tpu._private.config"


def _declared(index: ProjectIndex) -> Dict[str, int]:
    mod = index.module(_CONFIG_MODULE)
    out: Dict[str, int] = {}
    if mod is None:
        return out
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "_declare" and node.args \
                and isinstance(node.args[0], ast.Constant):
            out[node.args[0].value] = node.lineno
    return out


def _reads(index: ProjectIndex,
           declared: Dict[str, int]) -> Dict[str, List[Tuple[str, int, str]]]:
    """knob name -> [(relpath, line, enclosing symbol)] read sites."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}

    def add(mod, name: str, line: int) -> None:
        sym = mod.enclosing_function(line) or "<module>"
        out.setdefault(name, []).append((mod.relpath, line, sym))

    for mod in index.modules.values():
        if mod.modname == _CONFIG_MODULE:
            # Config's own machinery accesses flags generically; a
            # literal ``self.<knob>`` naming a DECLARED knob inside the
            # class (the timeout_scale scaling hook) counts as a read,
            # other self-attrs are ordinary instance state
            for node in mod.attr_loads:
                if isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in declared:
                    add(mod, node.attr, node.lineno)
            continue
        for node in mod.attr_loads:
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "CONFIG" \
                    and node.attr not in _CONFIG_METHODS:
                add(mod, node.attr, node.lineno)
        for node, _recv, name in mod.calls:
            if name == "getattr" and isinstance(node.func, ast.Name) \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id == "CONFIG" \
                    and isinstance(node.args[1], ast.Constant):
                add(mod, node.args[1].value, node.lineno)
    return out


def check(index: ProjectIndex) -> List[Violation]:
    declared = _declared(index)
    reads = _reads(index, declared)
    out: List[Violation] = []
    if not declared:
        return out
    config_mod = index.module(_CONFIG_MODULE)
    for name, sites in sorted(reads.items()):
        if name in declared:
            continue
        for relpath, line, sym in sites:
            out.append(Violation(
                RULE, relpath, line, sym,
                f"CONFIG.{name} is not a declared knob (would raise "
                f"AttributeError at runtime); _declare it in "
                f"_private/config.py or fix the name"))
    read_names: Set[str] = set(reads)
    for name, line in sorted(declared.items()):
        if name not in read_names:
            out.append(Violation(
                RULE, config_mod.relpath, line, name,
                f"declared knob {name!r} is never read inside the "
                f"package (dead knob: delete it, or justify a "
                f"test/env-only knob inline)"))
    return out
