"""async-blocking: no blocking calls inside ``async def`` bodies.

An event loop runs every coroutine of the process on one thread: a
single blocking call (``time.sleep``, a sync RPC, a ``Future.result``
wait, ``subprocess.run``) freezes EVERY request the loop is juggling —
the serve data path multiplexes 1k+ concurrent token streams over one
loop (docs/serve_disagg.md), so one blocked coroutine is a cluster-
visible latency cliff, not a local slowdown.  The sanctioned pattern is
``await loop.run_in_executor(None, blocking_fn)`` (wrapped in
``bind_ctx`` when the hop reads trace context — see the
executor-hop-context rule).

Matched violations are DIRECT calls in the async body (transitive
analysis lives in the inline-handler rule; here one hop is the
overwhelming bug shape).  ``await``-ed calls are exempt (``await
asyncio.wait(...)`` yields, it does not block), as are calls inside
nested sync ``def``s (those run wherever they're called from — usually
an executor).  ``asyncio.run()`` inside an ``async def`` is flagged
too: it is always a bug (cannot nest loops; the LLMEngine.warmup
incident).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.analysis import callgraph as cg
from ray_tpu._private.analysis.core import (ModuleInfo, ProjectIndex,
                                            Violation)

RULE = "async-blocking"
DESCRIPTION = ("blocking primitives called directly inside async def "
               "bodies (event-loop stalls)")

# attribute-call names that block; receiver-independent like the inline
# rule, but scoped to direct calls so the noise floor stays low
_BLOCKING_ATTRS = {
    "result": "Future.result() wait",
    "call": "synchronous RPC Connection.call",
    "communicate": "subprocess communicate",
    "check_output": "subprocess check_output",
    "check_call": "subprocess check_call",
}
# .wait( is handled specially: Event().wait blocks, but `await x.wait()`
# (asyncio.Event) is fine and covered by the await exemption


def _async_blocking(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    recv, name = cg.callee_parts(call)
    if name is None:
        return None
    if recv is not None:
        root = recv.split(".")[0]
        dotted = mod.imports.get(root)
        if dotted == "time" and name == "sleep":
            return "time.sleep"
        if dotted == "subprocess" and name in ("run", "check_output",
                                               "check_call"):
            return f"subprocess.{name}"
        if dotted == "asyncio" and name == "run":
            return "asyncio.run (nested event loop)"
        if name in _BLOCKING_ATTRS:
            return _BLOCKING_ATTRS[name]
        if name == "wait":
            return "Event/Condition/future wait"
        return None
    fi = mod.from_imports.get(name)
    if fi:
        if fi == ("time", "sleep"):
            return "time.sleep"
        if fi[0] == "subprocess":
            return f"subprocess.{fi[1]}"
    return None


def _awaited_calls(func: ast.AST) -> set:
    return {n.value for n in ast.walk(func)
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)}


def check(index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for mod in index.modules.values():
        for qual, func in mod.functions.items():
            if not isinstance(func, ast.AsyncFunctionDef):
                continue
            awaited = _awaited_calls(func)
            for call in cg.body_calls(func):
                if call in awaited:
                    continue
                desc = _async_blocking(mod, call)
                if desc:
                    out.append(Violation(
                        RULE, mod.relpath, call.lineno, qual,
                        f"blocking call in async def: {desc} (move it "
                        f"behind await loop.run_in_executor, or use the "
                        f"asyncio equivalent)"))
    return out
