"""executor-hop-context: ContextVars must survive executor hops.

``loop.run_in_executor`` and ``threading.Thread`` do NOT carry
ContextVars to the target thread.  Code that reads the tracing context
(util/tracing/tracing_helper.py ``current_context`` /
``get_trace_context`` / span opens) on the far side of such a hop sees
an EMPTY context — the serve http_proxy double-root bug: the fallback
path opened a second trace root per request because its executor hop
dropped the ingress root.  The contract is to wrap the target with
``tracing_helper.bind_ctx(ctx, fn, ...)``.

This checker flags ``run_in_executor``/``Thread(target=...)`` call
sites whose target callable resolves to a function that (transitively,
shallow) reads the trace context, unless the target is a ``bind_ctx``
call.  Long-lived daemon loops (flushers, heartbeats) legitimately run
context-free — those are allowlisted with a justification, which is the
documentation that the thread is context-free ON PURPOSE.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.analysis import callgraph as cg
from ray_tpu._private.analysis.core import (ModuleInfo, ProjectIndex,
                                            Violation)

RULE = "executor-hop-context"
DESCRIPTION = ("run_in_executor / Thread targets that read trace "
               "ContextVars must be wrapped with bind_ctx")

# context READS in tracing_helper (writes like propagate/install are the
# receiving side's job and fine to call anywhere)
_CTX_READERS = {"current_context", "get_trace_context",
                "maybe_sample_root", "span", "start_span",
                "start_ingress_root"}
_TRACING_MOD = "ray_tpu.util.tracing.tracing_helper"


def _reads_context(index: ProjectIndex, target: cg.Target,
                   depth: int = 3, _seen=None) -> Optional[str]:
    """Does ``target`` (shallow-transitively) read the trace context?
    Returns the reading callee name, or None."""
    if _seen is None:
        _seen = set()
    if target.key in _seen:
        return None
    _seen.add(target.key)
    # a function that installs its own context per item
    # (propagate_trace_context / install at the top of a daemon loop,
    # like the worker exec loop) manages the hop itself — reads past
    # the install see a real context, so don't scan or descend
    for call in cg.body_calls(target.node):
        _recv, name = cg.callee_parts(call)
        if name in ("propagate_trace_context", "install", "bind_ctx"):
            return None
    for call in cg.body_calls(target.node):
        _recv, name = cg.callee_parts(call)
        if name in _CTX_READERS:
            # must actually resolve to the tracing helper — a same-name
            # method elsewhere (e.g. a Perfetto `span` builder) is not
            # a context read
            resolved = cg.resolve_call(index, target.mod, target.qual,
                                       call)
            if any(t.mod.modname == _TRACING_MOD for t in resolved):
                return name
        if name == "get" and isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name) \
                and call.func.value.id.endswith("_ctx_var"):
            return "_ctx_var.get"
        if depth > 0:
            for nxt in cg.resolve_call(index, target.mod, target.qual,
                                       call):
                hit = _reads_context(index, nxt, depth - 1, _seen)
                if hit:
                    return hit
    return None


def _target_arg(mod: ModuleInfo, call: ast.Call) -> Optional[ast.AST]:
    """The callable being shipped across the hop, or None."""
    _recv, name = cg.callee_parts(call)
    if name == "run_in_executor":
        if len(call.args) >= 2:
            return call.args[1]
        return None
    if name == "Thread":
        # threading.Thread or from-imported Thread only
        recv, _ = cg.callee_parts(call)
        if recv is not None and mod.imports.get(recv) != "threading":
            return None
        if recv is None and mod.from_imports.get("Thread",
                                                 ("", ""))[0] != "threading":
            return None
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
    return None


def _is_bind_ctx(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        _recv, name = cg.callee_parts(node)
        if name == "bind_ctx":
            return True
        # functools.partial(bind_ctx(...)) etc: look one level in
        return any(_is_bind_ctx(a) for a in node.args)
    return False


def check(index: ProjectIndex) -> List[Violation]:
    out: List[Violation] = []
    for mod in index.modules.values():
        if mod.modname == _TRACING_MOD:
            continue  # the helper's own internals
        for call, _recv, name in mod.calls:
            if name not in ("run_in_executor", "Thread"):
                continue
            tgt = _target_arg(mod, call)
            if tgt is None or _is_bind_ctx(tgt):
                continue
            qual = mod.enclosing_function(call.lineno) or ""
            # resolve the shipped callable to a definition
            targets: List[cg.Target] = []
            if isinstance(tgt, (ast.Name, ast.Attribute)):
                fake = ast.Call(func=tgt, args=[], keywords=[])
                targets = cg.resolve_call(index, mod, qual, fake)
            reader = None
            for t in targets:
                reader = _reads_context(index, t)
                if reader:
                    break
            if reader:
                out.append(Violation(
                    RULE, mod.relpath, call.lineno,
                    qual or "<module>",
                    f"executor/thread hop target reads trace "
                    f"context via {reader}() but is not wrapped "
                    f"with tracing_helper.bind_ctx"))
    return out
