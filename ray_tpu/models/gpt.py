"""Flagship decoder-only transformer (LLaMA-style: RMSNorm/RoPE/SwiGLU/GQA).

TPU-first design notes:
  - params carry *logical* axis names via ``nn.with_logical_partitioning``;
    ray_tpu.parallel.sharding maps them to mesh axes (DP/FSDP/TP/SP from one
    rule table — the capability matrix the reference lacks, SURVEY.md §2.6).
  - layers run under ``lax.scan`` (one compiled block, O(1) compile time in
    depth) with optional remat (HBM <-> FLOPs trade).
  - attention dispatches to the Pallas flash kernel, plain XLA einsum, or
    ring attention over the mesh's ``context`` axis for long sequences.
  - decode uses a KV cache held in the flax ``cache`` collection
    (``decode`` is a module attribute, so it stays static under remat/scan).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.ops.attention import repeat_kv, xla_attention
from ray_tpu.ops.layers import apply_rope, rope_frequencies
from ray_tpu.parallel.sharding import LOGICAL_RULES, ShardingRules, with_sharding


def _dense(features, logical_axes, name=None, use_bias=False,
           param_dtype=jnp.float32, dtype=jnp.bfloat16):
    return nn.DenseGeneral(
        features=features, axis=-1, use_bias=use_bias, name=name,
        dtype=dtype, param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), logical_axes))


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale",
                           nn.with_logical_partitioning(
                               nn.initializers.ones_init(), ("norm",)),
                           (x.shape[-1],), jnp.float32)
        from ray_tpu.ops.layers import rms_norm
        return rms_norm(x, scale, self.eps)


class MLP(nn.Module):
    """SwiGLU feed-forward (shared by the decoder, encoder, and T5).

    FORMAT BREAK (round 1): extracting this submodule renamed parameter
    paths ``block_i/w_gate`` -> ``block_i/mlp/w_gate`` (same under scan).
    Checkpoints written before that refactor need their keys re-nested
    under ``mlp/`` to load; no shim is kept since no pre-break checkpoint
    left the repo."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, y):
        cfg = self.cfg
        gate = _dense(cfg.d_ff, ("embed", "mlp"), "w_gate",
                      dtype=cfg.dtype, param_dtype=cfg.param_dtype)(y)
        up = _dense(cfg.d_ff, ("embed", "mlp"), "w_up",
                    dtype=cfg.dtype, param_dtype=cfg.param_dtype)(y)
        return _dense(cfg.d_model, ("mlp", "embed"), "w_down",
                      dtype=cfg.dtype, param_dtype=cfg.param_dtype)(
            nn.silu(gate) * up)


def stack_layers(block_cls, cfg: TransformerConfig, ctor_kwargs, x,
                 call_args, *, remat: Optional[bool] = None,
                 cache: bool = False, name: str = "blocks",
                 n_layers: Optional[int] = None):
    """Apply ``n_layers`` (default cfg.n_layers) blocks under the repo's
    standard stacking: remat per cfg.remat (HBM<->FLOPs), one
    ``lax.scan``'d block when cfg.scan_layers (O(1) compile time in
    depth). Must be called from a parent's ``@nn.compact`` __call__.
    Blocks are invoked ``mdl(x, *call_args)``.

    cfg.remat_layers splits the stack at the CALLER (two stack_layers
    calls, one rematted, one plain) — partial remat for configs with
    HBM headroom between "recompute everything" and "store everything".
    """
    if n_layers is None:
        n_layers = cfg.n_layers
    if remat is None:
        remat = cfg.remat
    if remat:
        # the remat ladder, least to most memory (scaling-book recipe:
        # pick the most-saving policy that still fits HBM):
        #   nothing    — full recompute (fits 1B on one 16 GiB chip)
        #   block_outs — save each block's attn/mlp outputs (named
        #                checkpoints below): residual stream reconstructs
        #                without re-running attention, ~1.5 GiB at 1B/b8
        #   dots       — save only no-batch-dim dot outputs (tiny)
        #   dots_all   — save every dot output (max memory, min recompute)
        policies = {
            "nothing": None,
            "block_outs": jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_out"),
            "dots": jax.checkpoint_policies
            .dots_with_no_batch_dims_saveable,
            "dots_all": jax.checkpoint_policies.dots_saveable,
        }
        if cfg.remat_policy not in policies:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r}; "
                f"choose one of {sorted(policies)}")
        policy = policies[cfg.remat_policy]
        block_cls = nn.remat(block_cls, prevent_cse=False, policy=policy)
    if cfg.scan_layers:
        variable_axes = {"params": 0, "intermediates": 0}
        if cache:
            variable_axes["cache"] = 0
        x, _ = nn.scan(
            lambda mdl, carry, _: (mdl(carry, *call_args), None),
            variable_axes=variable_axes,
            split_rngs={"params": True},
            length=n_layers,
            metadata_params={nn.PARTITION_NAME: None},
        )(block_cls(cfg, **ctor_kwargs, name=name), x, None)
    else:
        for i in range(n_layers):
            x = block_cls(cfg, **ctor_kwargs,
                          name=f"{name[:-1]}_{i}")(x, *call_args)
    return x


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES
    decode: bool = False
    # paged decode (serve/llm_engine.py paged mode): KV lives in a shared
    # page pool instead of dense per-row [max_seq] strips.  paged_pages=0
    # keeps the dense layout.  See ops/paged_attention.py.
    paged_pages: int = 0
    page_size: int = 64
    # prefix-cache suffix prefill (serve/llm_engine.py): T > 1 windows
    # may start at nonzero positions over pages already holding a cached
    # prompt prefix, so attention must read back through the pool
    # instead of being causal over its own window only
    prefix_attend: bool = False

    @nn.compact
    def __call__(self, x, cos, sin, positions=None, block_tables=None):
        cfg = self.cfg
        h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        q = _dense((h, hd), ("embed", "heads", "head_dim"), "wq",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        k = _dense((kvh, hd), ("embed", "kv", "head_dim"), "wk",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        v = _dense((kvh, hd), ("embed", "kv", "head_dim"), "wv",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        if self.decode and self.paged_pages:
            out = self._decode_attend_paged(q, k, v, positions,
                                            block_tables)
        elif self.decode:
            out = self._decode_attend(q, k, v, positions)
        else:
            out = self._train_attend(q, k, v)
        out = out.reshape(*out.shape[:2], h * hd)
        return _dense(cfg.d_model, ("heads_embed", "embed"), "wo",
                      dtype=cfg.dtype, param_dtype=cfg.param_dtype)(out)

    def _train_attend(self, q, k, v):
        cfg = self.cfg
        impl = cfg.attention_impl
        if impl in ("ring", "ulysses"):
            if self.mesh is None:
                raise ValueError(f"{impl} attention requires a mesh")
            if impl == "ring":
                # the ring accumulator needs matched head counts
                if cfg.n_kv_heads != cfg.n_heads:
                    k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
                    v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
                from ray_tpu.ops.ring_attention import ring_attention
                return ring_attention(q, k, v, mesh=self.mesh, causal=True)
            # ulysses handles GQA natively (KV all-to-all stays at kv_heads);
            # only expand when kv_heads doesn't divide the context axis
            ctx = self.mesh.shape.get("context", 1)
            if cfg.n_kv_heads % ctx:
                k = repeat_kv(k, cfg.n_heads // cfg.n_kv_heads)
                v = repeat_kv(v, cfg.n_heads // cfg.n_kv_heads)
            from ray_tpu.ops.ulysses import ulysses_attention
            return ulysses_attention(q, k, v, mesh=self.mesh, causal=True)
        from ray_tpu.ops.attention import attention
        return attention(q, k, v, causal=True, impl=impl)

    def _decode_attend(self, q, k, v, positions):
        """Write K/V into the cache at per-row positions and attend under
        a position mask.

        ``positions`` [B, T] are the absolute positions of the q tokens;
        each row's T positions must be contiguous starting at
        ``positions[:, 0]`` but ROWS MAY SIT AT DIFFERENT OFFSETS — the
        property continuous batching needs (serve/llm_engine.py: each
        batch row is an independent request mid-decode).  The uniform
        case (Generator.generate) is positions = full(pos); when
        positions is None the scalar cache index drives a uniform step,
        the pre-slot behavior."""
        cfg = self.cfg
        b = q.shape[0]
        ck = self.variable("cache", "k", jnp.zeros,
                           (b, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim),
                           cfg.dtype)
        cv = self.variable("cache", "v", jnp.zeros,
                           (b, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim),
                           cfg.dtype)
        idx = self.variable("cache", "index", lambda: jnp.zeros((), jnp.int32))
        if self.is_initializing():
            # shape-only pass: leave the cache untouched (flax convention —
            # a cache write here would leave index advanced before decoding)
            return xla_attention(q, k, v, causal=True)
        if positions is None:
            positions = idx.value + jnp.broadcast_to(
                jnp.arange(q.shape[1]), (b, q.shape[1]))

        def _row_write(cache_row, new_row, p):
            return jax.lax.dynamic_update_slice(cache_row, new_row, (p, 0, 0))

        write_pos = positions[:, 0]
        ck.value = jax.vmap(_row_write)(ck.value, k.astype(cfg.dtype),
                                        write_pos)
        cv.value = jax.vmap(_row_write)(cv.value, v.astype(cfg.dtype),
                                        write_pos)
        idx.value = jnp.max(positions) + 1
        # key j is visible to the query at absolute position p iff j <= p
        # (equivalent to the old q_offset causal mask when rows align)
        k_idx = jnp.arange(cfg.max_seq_len)
        mask = k_idx[None, None, None, :] <= positions[:, None, :, None]
        return xla_attention(q, ck.value, cv.value, causal=False, mask=mask)

    def _decode_attend_paged(self, q, k, v, positions, block_tables):
        """Paged-pool decode: scatter this call's K/V into the rows' pages,
        then attend over only the occupied pages (ops/paged_attention.py).

        ``positions`` [B, T] as in ``_decode_attend``; ``block_tables``
        [B, max_pages] maps each row's logical page (position // page_size)
        to a physical page in the shared pool.  Prompt prefill is the
        T > 1 case: the window is causal over itself (a prompt attends
        only to its own prefix), so no pool read is needed — the scatter
        below is the whole cache interaction, and right-pad garbage past
        a real prompt is overwritten by decode writes before any length
        mask makes it visible (same invariant as dense slot mode).
        """
        cfg = self.cfg
        ps = self.page_size
        # one fused pool, K in [..., :hd], V in [..., hd:]; layout
        # dictated by TPU tiling (ops/paged_attention.py layout note)
        pool = (self.paged_pages, cfg.n_kv_heads, ps, 2 * cfg.head_dim)
        ckv = self.variable("cache", "kv_pages", jnp.zeros, pool,
                            cfg.dtype)
        if self.is_initializing():
            return xla_attention(q, k, v, causal=True)
        if positions is None or block_tables is None:
            raise ValueError("paged decode requires positions and "
                             "block_tables")
        pages = jnp.take_along_axis(block_tables, positions // ps, axis=1)
        offs = positions % ps
        kv = jnp.concatenate([k, v], axis=-1).astype(cfg.dtype)
        # advanced indices at dims 0 and 2 -> value layout [B, T, kvh, 2hd]
        ckv.value = ckv.value.at[pages, :, offs].set(kv)
        if q.shape[1] > 1:
            if not self.prefix_attend:
                return xla_attention(q, k, v, causal=True)
            # suffix prefill: the window's keys are NOT the whole story —
            # leading block-table entries hold a cached prompt prefix, so
            # gather the row's full logical span back out of the pool and
            # mask by absolute position (key j visible iff j <= query p).
            # Unallocated table entries point at scratch page 0, whose
            # garbage sits past every real query position.  Offset-0
            # windows reduce to the causal case (their own keys were just
            # scattered), so this path is correct for any offset.
            b = q.shape[0]
            gathered = ckv.value[block_tables]   # [B, mp, kvh, ps, 2hd]
            kvfull = jnp.moveaxis(gathered, 3, 2).reshape(
                b, -1, cfg.n_kv_heads, 2 * cfg.head_dim)
            k_idx = jnp.arange(kvfull.shape[1])
            mask = k_idx[None, None, None, :] <= \
                positions[:, None, :, None]
            return xla_attention(q, kvfull[..., :cfg.head_dim],
                                 kvfull[..., cfg.head_dim:],
                                 causal=False, mask=mask)
        from ray_tpu.ops.paged_attention import paged_attention
        out = paged_attention(q[:, 0], ckv.value, block_tables,
                              positions[:, 0] + 1)
        return out[:, None]


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES
    decode: bool = False
    paged_pages: int = 0
    page_size: int = 64
    prefix_attend: bool = False

    @nn.compact
    def __call__(self, x, cos, sin, positions=None, block_tables=None):
        cfg = self.cfg
        y = RMSNorm(cfg.norm_eps, name="attn_norm")(x)
        y = Attention(cfg, self.mesh, self.rules, self.decode,
                      self.paged_pages, self.page_size,
                      self.prefix_attend, name="attn")(
            y, cos, sin, positions, block_tables)
        y = jax.ad_checkpoint.checkpoint_name(y, "attn_out")
        x = x + y
        y = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        if cfg.moe_experts > 0:
            from ray_tpu.ops.moe import MoEMLP
            y = MoEMLP(cfg.moe_experts, cfg.d_ff, top_k=cfg.moe_top_k,
                       capacity_factor=cfg.moe_capacity_factor,
                       aux_loss_coef=cfg.moe_aux_coef,
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       name="moe")(y)
        else:
            y = MLP(cfg, name="mlp")(y)
        y = jax.ad_checkpoint.checkpoint_name(y, "mlp_out")
        x = x + y
        if self.mesh is not None and not self.decode:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        return x


class GPT(nn.Module):
    """Decoder-only LM.  ``__call__`` returns logits [B, S, vocab], or the
    post-final-norm hidden states [B, S, d_model] with
    ``return_hidden=True`` (the chunked-loss head path)."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES
    decode: bool = False
    paged_pages: int = 0                   # >0: paged KV decode (see Attention)
    page_size: int = 64
    prefix_attend: bool = False            # suffix prefill over cached pages

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden: bool = False,
                 block_tables=None):
        cfg = self.cfg
        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        lookup = embed
        if self.mesh is not None and not self.decode:
            # explicit all-gather of the sharded table before the lookup:
            # left to itself the partitioner reshards the gather result
            # via an involuntary full rematerialization (replicate, then
            # repartition — a full-tensor broadcast on the step's hot
            # path).  Constraining the operand makes the same transfer
            # ONE clean all-gather and the gather itself local.
            lookup = with_sharding(self.mesh, embed, (None, None),
                                   self.rules)
        x = jnp.take(lookup, tokens, axis=0).astype(cfg.dtype)
        if self.mesh is not None and not self.decode:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        if self.mesh is not None and not self.decode:
            # the rope tables are tiny closure constants: pin them
            # replicated so the partitioner never invents a sharding for
            # them (they otherwise surface as involuntarily
            # rematerialized fake_parameters)
            cos = with_sharding(self.mesh, cos, (None, None), self.rules)
            sin = with_sharding(self.mesh, sin, (None, None), self.rules)

        do_remat = cfg.remat and not self.decode
        n_remat = (cfg.n_layers if cfg.remat_layers is None
                   else max(0, min(cfg.remat_layers, cfg.n_layers)))
        block_kwargs = dict(mesh=self.mesh, rules=self.rules,
                            decode=self.decode,
                            paged_pages=self.paged_pages,
                            page_size=self.page_size,
                            prefix_attend=self.prefix_attend)
        call_args = (cos, sin, positions, block_tables)
        if do_remat and 0 < n_remat < cfg.n_layers:
            # partial remat: the first n_remat layers recompute in the
            # backward pass, the tail stores activations (uses the HBM
            # headroom "policy" selection can't reach)
            x = stack_layers(Block, cfg, block_kwargs, x,
                             call_args, remat=True,
                             cache=True, n_layers=n_remat)
            x = stack_layers(Block, cfg, block_kwargs, x,
                             call_args, remat=False,
                             cache=True, name="blocks_tail",
                             n_layers=cfg.n_layers - n_remat)
        else:
            x = stack_layers(Block, cfg, block_kwargs, x,
                             call_args, remat=do_remat,
                             cache=True)

        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if return_hidden:
            # memory-lean loss path: the caller projects per sequence
            # chunk (ops/losses.py chunked_lm_loss) so [B, S, vocab]
            # logits never materialize
            return x
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
        else:
            logits = _dense(cfg.vocab_size, ("embed", "vocab"), "lm_head",
                            dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        if self.mesh is not None and not self.decode:
            logits = with_sharding(self.mesh, logits,
                                   ("batch", "seq", "act_vocab"), self.rules)
        return logits.astype(jnp.float32)
