"""Autoregressive decoding: KV-cache generation with standard samplers.

Inference counterpart of the GPT decode path (ray_tpu/models/gpt.py
``decode=True``: cache collection + rotary offsets).  The per-token step is
one jitted function (prefill is a single wide step at offset 0), and the
sampler supports temperature / top-k / nucleus (top-p) — the decoding
surface an LLM Serve deployment needs (the reference's Serve LLM benchmark
surface, BASELINE.md llama3-8b row).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.gpt import GPT


def _trim_logits(logits: jax.Array, top_k: int, top_p: float) -> jax.Array:
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def sample_logits(rng: jax.Array, logits: jax.Array, *,
                  temperature=1.0, top_k: int = 0,
                  top_p: float = 1.0) -> jax.Array:
    """Sample token ids from [B, V] logits (greedy when temperature == 0).

    ``temperature`` may be a scalar or a per-row [B] array — the
    continuous-batching engine mixes greedy and sampled requests in one
    batch, so greedy rows (temperature 0) select argmax under the same
    trace."""
    if isinstance(temperature, (int, float)):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(
            rng, _trim_logits(logits / temperature, top_k, top_p), axis=-1)
    temps = jnp.asarray(temperature)
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temps > 0, temps, 1.0)
    trimmed = _trim_logits(logits / safe_t[:, None], top_k, top_p)
    sampled = jax.random.categorical(rng, trimmed, axis=-1)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def init_decode_cache(model, batch_size: int):
    """Zeroed KV cache for a decode-mode model, built from shapes alone
    (eval_shape — no second copy of the parameters is materialized).
    Shared by Generator and the serving engine (serve/llm_engine.py)."""
    tokens = jnp.zeros((batch_size, 1), jnp.int32)
    abstract = jax.eval_shape(
        lambda t: model.init(jax.random.PRNGKey(0), t), tokens)
    return jax.tree.map(lambda a: jnp.zeros(a.shape, a.dtype),
                        abstract["cache"])


class Generator:
    """Holds a decode-mode model + jitted prefill/step for repeated calls."""

    def __init__(self, cfg: TransformerConfig, params,
                 mesh=None):
        self.cfg = cfg
        self.params = params
        self.model = GPT(cfg, mesh=mesh, decode=True)

        def prefill(params, cache, tokens):
            b, s = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, tokens, positions,
                mutable=["cache"])
            return logits[:, -1], mut["cache"]

        def step(params, cache, token, pos):
            positions = pos[:, None]
            logits, mut = self.model.apply(
                {"params": params, "cache": cache}, token[:, None],
                positions, mutable=["cache"])
            return logits[:, -1], mut["cache"]

        self._prefill = jax.jit(prefill)
        self._step = jax.jit(step, donate_argnums=(1,))

    def init_cache(self, batch_size: int):
        return init_decode_cache(self.model, batch_size)

    def generate(self, prompt_tokens, *, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, eos_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """prompt_tokens [B, S] -> generated ids [B, <=max_new_tokens].

        Stops early only when *every* row has emitted ``eos_id``; rows that
        finished earlier keep their first eos and are padded with it.
        """
        prompt_tokens = jnp.asarray(prompt_tokens, jnp.int32)
        b, s = prompt_tokens.shape
        if s + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt {s} + {max_new_tokens} new > max_seq_len "
                f"{self.cfg.max_seq_len}")
        if max_new_tokens <= 0:
            return jnp.zeros((b, 0), jnp.int32)
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        sampler = functools.partial(sample_logits, temperature=temperature,
                                    top_k=top_k, top_p=top_p)
        cache = self.init_cache(b)
        logits, cache = self._prefill(self.params, cache, prompt_tokens)
        out = []
        done = jnp.zeros((b,), bool)
        for i in range(max_new_tokens):
            rng, key = jax.random.split(rng)
            token = sampler(key, logits)
            if eos_id is not None:
                token = jnp.where(done, eos_id, token)
                done = done | (token == eos_id)
            out.append(token)
            last = i == max_new_tokens - 1
            if last or (eos_id is not None and bool(done.all())):
                break   # the logits for a further token are never needed
            pos = jnp.full((b,), s + i, jnp.int32)
            logits, cache = self._step(self.params, cache, token, pos)
        return jnp.stack(out, axis=1)


def generate(cfg: TransformerConfig, params, prompt_tokens,
             **kwargs) -> jnp.ndarray:
    """One-shot convenience wrapper around Generator."""
    return Generator(cfg, params).generate(prompt_tokens, **kwargs)
