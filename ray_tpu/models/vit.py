"""ViT: Vision Transformer image classifier.

The reference's vision path is torchvision models driven through Train/AIR
(/root/reference/doc/source/ray-air/benchmarks.rst GPU image training;
python/ray/train/torch/). This is the TPU-native counterpart to its
transformer-based vision models: patchify → shared bidirectional Encoder
(ray_tpu/models/encoder.py, same sharded kernels as the LM) → mean-pool →
linear head. Drop-in for make_vision_train (no BatchNorm state).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.encoder import Encoder, learned_positions
from ray_tpu.models.gpt import _dense
from ray_tpu.parallel.sharding import LOGICAL_RULES, ShardingRules, with_sharding


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    encoder: TransformerConfig = None          # set in __post_init__

    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: Optional[int] = None
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    remat: bool = False
    scan_layers: bool = True

    def __post_init__(self):
        assert self.image_size % self.patch_size == 0
        if self.encoder is None:
            self.encoder = TransformerConfig(
                vocab_size=1,  # unused by the encoder body
                d_model=self.d_model, n_layers=self.n_layers,
                n_heads=self.n_heads, d_ff=self.d_ff,
                max_seq_len=self.num_patches,
                dtype=self.dtype, param_dtype=self.param_dtype,
                remat=self.remat, scan_layers=self.scan_layers)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


VIT_PRESETS = {
    "vit-tiny-test": ViTConfig(image_size=32, patch_size=8, num_classes=10,
                               d_model=64, n_layers=2, n_heads=4, d_ff=128,
                               dtype=jnp.float32),
    "vit-s16": ViTConfig(d_model=384, n_layers=12, n_heads=6),
    "vit-b16": ViTConfig(d_model=768, n_layers=12, n_heads=12),
    "vit-l16": ViTConfig(d_model=1024, n_layers=24, n_heads=16),
}


def get_vit_config(name: str, **overrides) -> ViTConfig:
    # always copy: presets are shared mutable dataclasses
    return dataclasses.replace(VIT_PRESETS[name], encoder=None, **overrides)


class ViT(nn.Module):
    """__call__(images [B, H, W, C]) -> logits [B, num_classes]."""

    cfg: ViTConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        enc = cfg.encoder
        p = cfg.patch_size
        b, hh, ww, c = images.shape
        # patchify: [B, H/p, W/p, p*p*C] — a reshape, not a conv; the
        # projection below is then one big MXU matmul
        x = images.reshape(b, hh // p, p, ww // p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, cfg.num_patches, p * p * c)
        x = x.astype(enc.dtype)
        x = _dense(enc.d_model, ("conv_io", "embed"), "patch_proj",
                   dtype=enc.dtype, param_dtype=enc.param_dtype)(x)
        x = x + learned_positions(enc, self, cfg.num_patches).astype(enc.dtype)
        if self.mesh is not None:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        x = Encoder(enc, self.mesh, self.rules, name="encoder")(x)
        x = jnp.mean(x, axis=1)                # mean-pool (no cls token)
        logits = _dense(cfg.num_classes, ("embed", "vocab"), "head",
                        dtype=enc.dtype, param_dtype=enc.param_dtype)(x)
        return logits.astype(jnp.float32)
