"""Shared bidirectional transformer encoder stack (ViT / BERT / T5 body).

The reference frames these families through torch/HF integrations
(/root/reference/python/ray/train/huggingface/, air examples); here they
are first-class flax modules sharing the decoder's TPU design: logical
axis names on every kernel (DP/FSDP/TP from the one rule table in
ray_tpu/parallel/sharding.py), bf16 activations, lax.scan over layers,
optional remat, and attention dispatched to the same kernels
(ray_tpu/ops/attention.py) — just non-causal.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.gpt import MLP, RMSNorm, _dense, stack_layers
from ray_tpu.ops.attention import attention, repeat_kv
from ray_tpu.parallel.sharding import LOGICAL_RULES, ShardingRules, with_sharding


class EncoderAttention(nn.Module):
    """Bidirectional (optionally cross-) attention with logical sharding."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, kv=None, mask=None):
        cfg = self.cfg
        h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        kv_in = x if kv is None else kv
        q = _dense((h, hd), ("embed", "heads", "head_dim"), "wq",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        k = _dense((kvh, hd), ("embed", "kv", "head_dim"), "wk",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(kv_in)
        v = _dense((kvh, hd), ("embed", "kv", "head_dim"), "wv",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(kv_in)
        if kvh != h:
            k = repeat_kv(k, h // kvh)
            v = repeat_kv(v, h // kvh)
        impl = cfg.attention_impl
        if impl in ("ring", "ulysses"):       # context axes are causal-LM
            impl = "auto"                      # machinery; encoders use core
        out = attention(q, k, v, causal=False, impl=impl, mask=mask)
        out = out.reshape(*out.shape[:2], h * hd)
        return _dense(cfg.d_model, ("heads_embed", "embed"), "wo",
                      dtype=cfg.dtype, param_dtype=cfg.param_dtype)(out)


class EncoderBlock(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        y = RMSNorm(cfg.norm_eps, name="attn_norm")(x)
        y = EncoderAttention(cfg, name="attn")(y, mask=mask)
        x = x + y
        y = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        x = x + MLP(cfg, name="mlp")(y)
        if self.mesh is not None:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        return x


class Encoder(nn.Module):
    """Stack of bidirectional blocks; input is an embedded sequence."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, x, mask=None):
        cfg = self.cfg
        x = stack_layers(EncoderBlock, cfg,
                         dict(mesh=self.mesh, rules=self.rules),
                         x, (mask,))
        return RMSNorm(cfg.norm_eps, name="final_norm")(x)


def learned_positions(cfg: TransformerConfig, module: nn.Module,
                      length: int) -> jax.Array:
    """Learned absolute position table (BERT/ViT style)."""
    return module.param(
        "pos_embed",
        nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), (None, "embed")),
        (length, cfg.d_model), cfg.param_dtype)
