"""BERT-family masked-LM encoder.

The reference serves/trains BERT through HF wrappers
(/root/reference/python/ray/train/huggingface/huggingface_trainer.py);
here it is native: learned positions + shared bidirectional Encoder (same
sharded kernels as the LM) + tied-embedding MLM head. Padding flows as a
key mask into the attention op. ``mlm_loss_fn`` plugs into
make_sharded_train; ``masked_batch`` builds BERT's 80/10/10 corruption.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.encoder import Encoder, learned_positions
from ray_tpu.models.gpt import RMSNorm, _dense
from ray_tpu.parallel.sharding import LOGICAL_RULES, ShardingRules, with_sharding

IGNORE = -100                      # label value for unmasked positions


class BERT(nn.Module):
    """__call__(tokens [B, S], attn_mask [B, S]?) -> logits [B, S, vocab]."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, tokens, attn_mask=None):
        cfg = self.cfg
        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
        x = x + learned_positions(cfg, self, cfg.max_seq_len)[
            : tokens.shape[1]].astype(cfg.dtype)
        if self.mesh is not None:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        mask = None if attn_mask is None else attn_mask.astype(jnp.bool_)
        x = Encoder(cfg, self.mesh, self.rules, name="encoder")(x, mask)
        x = _dense(cfg.d_model, ("embed", "act_embed"), "mlm_transform",
                   dtype=cfg.dtype, param_dtype=cfg.param_dtype)(x)
        x = nn.gelu(x)
        x = RMSNorm(cfg.norm_eps, name="mlm_norm")(x)
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
        return logits.astype(jnp.float32)


def mlm_loss_fn(apply_fn, params, batch: Dict[str, jax.Array],
                z_loss: float = 0.0) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Masked-LM loss: batch {"tokens", "labels" (IGNORE=-100 elsewhere),
    "attn_mask"?}. Plugs into make_sharded_train(loss_fn=...)."""
    logits = apply_fn({"params": params}, batch["tokens"],
                      batch.get("attn_mask"))
    labels = batch["labels"]
    valid = labels != IGNORE
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(valid.sum(), 1)
    loss = jnp.where(valid, nll, 0.0).sum() / denom
    if z_loss:
        zl = jnp.square(jax.scipy.special.logsumexp(logits, axis=-1))
        loss = loss + z_loss * jnp.where(valid, zl, 0.0).sum() / denom
    acc = (jnp.where(valid, jnp.argmax(logits, -1) == safe, False).sum()
           / denom)
    return loss, {"loss": loss, "mlm_accuracy": acc,
                  "masked_tokens": denom}


def masked_batch(tokens: np.ndarray, vocab_size: int, *,
                 mask_token: int, mask_prob: float = 0.15,
                 seed: int = 0) -> Dict[str, np.ndarray]:
    """BERT corruption: of the selected 15%, 80% -> [MASK], 10% -> random,
    10% unchanged; labels carry the original ids, IGNORE elsewhere."""
    rng = np.random.default_rng(seed)
    tokens = np.asarray(tokens)
    sel = rng.random(tokens.shape) < mask_prob
    labels = np.where(sel, tokens, IGNORE)
    r = rng.random(tokens.shape)
    corrupted = tokens.copy()
    corrupted[sel & (r < 0.8)] = mask_token
    rand_ids = rng.integers(0, vocab_size, tokens.shape)
    swap = sel & (r >= 0.8) & (r < 0.9)
    corrupted[swap] = rand_ids[swap]
    return {"tokens": corrupted, "labels": labels}
