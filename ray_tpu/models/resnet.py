"""ResNet family (v1.5): the vision baseline model.

BASELINE.md's vision reference is ResNet-50 data-parallel training (the
reference's GPU training benchmark, doc/source/ray-air/benchmarks.rst
:158-174); SURVEY.md §7 phase 4 names ResNet-50/CIFAR-10 as the first
end-to-end slice.  Flax implementation, NHWC (TPU-native conv layout),
bfloat16 activations with fp32 batch-norm statistics; trains under the same
make_sharded_train harness as the transformers via ``classification_loss_fn``
(ray_tpu/train/step.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """images [B, H, W, C] (NHWC) -> logits [B, num_classes]."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_inputs: bool = False     # CIFAR-style stem (3x3, no maxpool)
    train: bool = True

    @nn.compact
    def __call__(self, x):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype,
                                 padding="SAME")
        norm = functools.partial(nn.BatchNorm, use_running_average=not
                                 self.train, momentum=0.9, epsilon=1e-5,
                                 dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_inputs:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     kernel_init=nn.with_logical_partitioning(
                         nn.initializers.lecun_normal(),
                         ("embed", "vocab")))(x)
        return x.astype(jnp.float32)


def _preset(stages, block) -> Callable[..., ResNet]:
    def make(num_classes: int = 1000, **kwargs) -> ResNet:
        return ResNet(stage_sizes=stages, block_cls=block,
                      num_classes=num_classes, **kwargs)
    return make


ResNet18 = _preset([2, 2, 2, 2], BasicBlock)
ResNet34 = _preset([3, 4, 6, 3], BasicBlock)
ResNet50 = _preset([3, 4, 6, 3], BottleneckBlock)
ResNet101 = _preset([3, 4, 23, 3], BottleneckBlock)
