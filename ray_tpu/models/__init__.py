"""Model families: flagship GPT (LLaMA-style) LM, ResNet vision models."""

from ray_tpu.models.configs import PRESETS, TransformerConfig, get_config
from ray_tpu.models.generate import Generator, generate, sample_logits
from ray_tpu.models.gpt import GPT
from ray_tpu.models.resnet import (ResNet, ResNet18, ResNet34, ResNet50,
                                   ResNet101)

__all__ = ["GPT", "TransformerConfig", "PRESETS", "get_config",
           "Generator", "generate", "sample_logits",
           "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101"]
