"""Model families: flagship GPT (LLaMA-style) LM, encoder family (BERT,
ViT), T5 encoder-decoder, ResNet vision models."""

from ray_tpu.models.bert import BERT, masked_batch, mlm_loss_fn
from ray_tpu.models.configs import PRESETS, TransformerConfig, get_config
from ray_tpu.models.encoder import Encoder, EncoderBlock
from ray_tpu.models.generate import Generator, generate, sample_logits
from ray_tpu.models.gpt import GPT
from ray_tpu.models.resnet import (ResNet, ResNet18, ResNet34, ResNet50,
                                   ResNet101)
from ray_tpu.models.t5 import (T5, greedy_decode, seq2seq_loss_fn,
                               t5_init_inputs)
from ray_tpu.models.vit import VIT_PRESETS, ViT, ViTConfig, get_vit_config

__all__ = ["GPT", "TransformerConfig", "PRESETS", "get_config",
           "Generator", "generate", "sample_logits",
           "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101",
           "BERT", "mlm_loss_fn", "masked_batch",
           "Encoder", "EncoderBlock",
           "T5", "seq2seq_loss_fn", "t5_init_inputs", "greedy_decode",
           "ViT", "ViTConfig", "VIT_PRESETS", "get_vit_config"]
