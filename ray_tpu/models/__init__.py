"""Model families: flagship GPT (LLaMA-style) LM, ResNet vision models."""

from ray_tpu.models.configs import PRESETS, TransformerConfig, get_config
from ray_tpu.models.gpt import GPT
from ray_tpu.models.resnet import (ResNet, ResNet18, ResNet34, ResNet50,
                                   ResNet101)

__all__ = ["GPT", "TransformerConfig", "PRESETS", "get_config",
           "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101"]
