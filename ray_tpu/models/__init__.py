"""Model families: flagship GPT (LLaMA-style) LM, ResNet vision models."""

from ray_tpu.models.configs import PRESETS, TransformerConfig, get_config
from ray_tpu.models.gpt import GPT

__all__ = ["GPT", "TransformerConfig", "PRESETS", "get_config"]
