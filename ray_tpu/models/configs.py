"""Model configuration presets.

Sizes chosen to line up with the reference's benchmark families
(BASELINE.md: GPT-J-6B pretraining is the Train north-star; resnet50 the
vision baseline) plus tiny configs for tests.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None       # None -> = n_heads (MHA)
    d_ff: Optional[int] = None             # None -> 4 * d_model (8/3 for swiglu)
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: jnp.dtype = jnp.bfloat16        # activation dtype
    param_dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"           # auto | xla | flash | splash | ring | ulysses
    remat: bool = True                     # checkpoint each block (HBM <-> FLOPs)
    remat_layers: Optional[int] = None     # None -> all; K -> only the first
    # K layers rematerialize, the rest store activations (partial remat:
    # spends HBM headroom to cut the backward recompute, the knob between
    # "nothing" and no-remat that per-policy selection can't reach)
    remat_policy: str = "dots"             # "dots": save no-batch-dim dots
    # (cheap recompute, more HBM); "nothing": full per-block recompute —
    # the memory-lean setting that fits ~1B params on one 16 GiB chip
    scan_layers: bool = True               # lax.scan over layers
    tie_embeddings: bool = False
    z_loss: float = 1e-4
    # Mixture-of-experts (0 -> dense MLP).  Experts shard over the mesh's
    # data axes (expert parallelism, ray_tpu/ops/moe.py).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    def __post_init__(self):
        if self.n_kv_heads is None:
            self.n_kv_heads = self.n_heads
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        assert self.d_model % self.n_heads == 0
        assert self.n_heads % self.n_kv_heads == 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        emb = self.vocab_size * self.d_model * (1 if self.tie_embeddings else 2)
        attn = self.d_model * self.head_dim * (
            self.n_heads * 2 + self.n_kv_heads * 2)
        mlp = 3 * self.d_model * self.d_ff
        norms = 2 * self.d_model
        return emb + self.n_layers * (attn + mlp + norms) + self.d_model


PRESETS = {
    # test-size
    "tiny": TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                              n_heads=4, d_ff=128, max_seq_len=128,
                              dtype=jnp.float32, remat=False),
    # test-size MoE (8 experts, top-2)
    "tiny-moe": TransformerConfig(vocab_size=256, d_model=64, n_layers=2,
                                  n_heads=4, d_ff=128, max_seq_len=128,
                                  dtype=jnp.float32, remat=False,
                                  moe_experts=8, moe_top_k=2),
    # Mixtral-8x7B shapes (headline open MoE family)
    "mixtral-8x7b": TransformerConfig(vocab_size=32000, d_model=4096,
                                      n_layers=32, n_heads=32, n_kv_heads=8,
                                      d_ff=14336, max_seq_len=8192,
                                      rope_theta=1e6, moe_experts=8,
                                      moe_top_k=2),
    # ~124M GPT-2 small shapes
    "gpt-small": TransformerConfig(vocab_size=50304, d_model=768, n_layers=12,
                                   n_heads=12, max_seq_len=1024),
    # ~350M GPT-2 medium shapes (largest config whose fp32 AdamW states +
    # remat activations fit one 16 GiB v5e chip with headroom)
    "gpt-medium": TransformerConfig(vocab_size=50304, d_model=1024,
                                    n_layers=24, n_heads=16,
                                    max_seq_len=1024),
    # GPT-2 large shapes — ~1.07B params with the SwiGLU MLP (needs the
    # memory-lean path on a single 16 GiB chip: full remat + chunked CE +
    # adafactor; comfortable under fsdp on 2+)
    "gpt-large": TransformerConfig(vocab_size=50304, d_model=1280,
                                   n_layers=36, n_heads=20,
                                   max_seq_len=1024),
    # ~1.3B
    "gpt-xl": TransformerConfig(vocab_size=50304, d_model=2048, n_layers=24,
                                n_heads=16, max_seq_len=2048),
    # GPT-J-6B shapes (the reference Train benchmark model, BASELINE.md)
    "gptj-6b": TransformerConfig(vocab_size=50400, d_model=4096, n_layers=28,
                                 n_heads=16, max_seq_len=2048),
    # LLaMA-3-8B shapes (the reference Serve benchmark model, BASELINE.md)
    "llama3-8b": TransformerConfig(vocab_size=128256, d_model=4096,
                                   n_layers=32, n_heads=32, n_kv_heads=8,
                                   d_ff=14336, max_seq_len=8192,
                                   rope_theta=500000.0),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    base = PRESETS[name]
    return dataclasses.replace(base, **overrides) if overrides else base
