"""T5-style encoder-decoder for seq2seq.

The reference reaches seq2seq via HF Transformers wrappers
(/root/reference/python/ray/train/huggingface/); this is the native
TPU-first version: shared bidirectional Encoder for the source, a decoder
whose blocks are causal self-attention (RoPE, same kernels as the LM) +
cross-attention over the encoder memory + the shared SwiGLU MLP, all
carrying the logical sharding axes of ray_tpu/parallel/sharding.py. RoPE
replaces T5's relative-position bias — same role, better fit for the fused
attention kernels. ``seq2seq_loss_fn`` plugs into make_sharded_train.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models.configs import TransformerConfig
from ray_tpu.models.encoder import Encoder, EncoderAttention
from ray_tpu.models.gpt import MLP, Attention, RMSNorm, stack_layers
from ray_tpu.ops.layers import rope_frequencies
from ray_tpu.ops.losses import softmax_cross_entropy
from ray_tpu.parallel.sharding import LOGICAL_RULES, ShardingRules, with_sharding


class DecoderBlock(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, x, memory, cos, sin, enc_mask=None):
        cfg = self.cfg
        y = RMSNorm(cfg.norm_eps, name="self_norm")(x)
        y = Attention(cfg, self.mesh, self.rules, name="self_attn")(
            y, cos, sin)
        x = x + y
        y = RMSNorm(cfg.norm_eps, name="cross_norm")(x)
        y = EncoderAttention(cfg, name="cross_attn")(y, kv=memory,
                                                     mask=enc_mask)
        x = x + y
        y = RMSNorm(cfg.norm_eps, name="mlp_norm")(x)
        x = x + MLP(cfg, name="mlp")(y)
        if self.mesh is not None:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        return x


class T5(nn.Module):
    """__call__(enc_tokens [B, Se], dec_tokens [B, Sd], enc_mask [B, Se]?)
    -> logits [B, Sd, vocab]. One shared vocab/embedding (t5 convention).

    ``memory=...`` skips the encoder (decode loops encode once, then feed
    the cached memory); ``return_memory=True`` returns the encoder output
    instead of logits. Both are Python-level (static) switches.
    """

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    rules: ShardingRules = LOGICAL_RULES

    @nn.compact
    def __call__(self, enc_tokens, dec_tokens, enc_mask=None,
                 memory=None, return_memory: bool = False):
        cfg = self.cfg
        embed = self.param(
            "embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)

        mask = None if enc_mask is None else enc_mask.astype(jnp.bool_)
        if memory is None:
            src = jnp.take(embed, enc_tokens, axis=0).astype(cfg.dtype)
            if self.mesh is not None:
                src = with_sharding(self.mesh, src,
                                    ("batch", "seq", "act_embed"),
                                    self.rules)
            memory = Encoder(cfg, self.mesh, self.rules, name="encoder")(
                src, mask)
        if return_memory:
            return memory

        x = jnp.take(embed, dec_tokens, axis=0).astype(cfg.dtype)
        if self.mesh is not None:
            x = with_sharding(self.mesh, x, ("batch", "seq", "act_embed"),
                              self.rules)
        cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len,
                                    cfg.rope_theta)
        x = stack_layers(DecoderBlock, cfg,
                         dict(mesh=self.mesh, rules=self.rules),
                         x, (memory, cos, sin, mask))
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
        return logits.astype(jnp.float32)


def seq2seq_loss_fn(apply_fn, params, batch: Dict[str, jax.Array],
                    z_loss: float = 0.0
                    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Teacher-forced seq2seq loss: batch {"enc_tokens" [B, Se],
    "dec_tokens" [B, Sd+1], "enc_mask"?, "dec_mask"? [B, Sd+1]}.
    Plugs into make_sharded_train(loss_fn=..., init_inputs=t5_init_inputs).
    """
    dec = batch["dec_tokens"]
    inputs, targets = dec[:, :-1], dec[:, 1:]
    mask = batch.get("dec_mask")
    if mask is not None:
        mask = mask[:, 1:].astype(jnp.float32)
    logits = apply_fn({"params": params}, batch["enc_tokens"], inputs,
                      batch.get("enc_mask"))
    loss, denom = softmax_cross_entropy(logits, targets, mask, z_loss)
    return loss, {"loss": loss, "tokens": denom}


def t5_init_inputs(batch):
    """make_sharded_train init_inputs for T5's (enc, dec) signature."""
    return (batch["enc_tokens"], batch["dec_tokens"][:, :-1],
            batch.get("enc_mask"))


def greedy_decode(model: T5, variables, enc_tokens, *, max_len: int,
                  bos_id: int, eos_id: Optional[int] = None,
                  enc_mask=None):
    """Greedy decoding with the encoder run once and a fixed-shape decoder
    buffer (one compile for the whole loop; causal attention makes the
    trailing zero-padding inert). The flagship KV-cached decode path lives
    in models/generate.py for the decoder-only family.
    """
    b = enc_tokens.shape[0]
    encode = jax.jit(lambda v, e, m: model.apply(
        v, e, jnp.zeros((e.shape[0], 1), jnp.int32), m, return_memory=True))
    step = jax.jit(lambda v, e, d, m, mem, i: jnp.argmax(
        model.apply(v, e, d, m, memory=mem)[
            jnp.arange(d.shape[0]), i], axis=-1).astype(jnp.int32))

    memory = encode(variables, enc_tokens, enc_mask)
    dec = jnp.zeros((b, max_len + 1), jnp.int32).at[:, 0].set(bos_id)
    finished = jnp.zeros((b,), bool)
    n_emitted = 0
    for i in range(max_len):
        nxt = step(variables, enc_tokens, dec, enc_mask, memory, i)
        if eos_id is not None:
            nxt = jnp.where(finished, eos_id, nxt)
            finished = finished | (nxt == eos_id)
        dec = dec.at[:, i + 1].set(nxt)
        n_emitted = i + 1
        if eos_id is not None and bool(finished.all()):
            break
    return dec[:, 1:n_emitted + 1]
