"""Training-loop session: the channel between user train code and the
framework driving it.

Analog of /root/reference/python/ray/air/session.py (report :41,
get_dataset_shard :345) + train/_internal/session.py. The trainer (or Tune
function-trainable runner) installs a ``_Session`` in the worker before
calling the user loop; ``report`` hands (metrics, checkpoint) to it and
blocks until the driver has consumed the result — the same
producer/consumer handshake the reference builds with a result queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class _Session:
    def __init__(self, *, world_rank: int = 0, world_size: int = 1,
                 local_rank: int = 0, local_world_size: int = 1,
                 node_rank: int = 0,
                 trial_name: str = "", trial_id: str = "",
                 trial_dir: str = "", experiment_name: str = "",
                 dataset_shards: Optional[Dict[str, Any]] = None,
                 checkpoint: Optional[Checkpoint] = None,
                 synchronous: bool = True):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.local_world_size = local_world_size
        self.node_rank = node_rank
        self.trial_name = trial_name
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.experiment_name = experiment_name
        self.dataset_shards = dataset_shards or {}
        self.loaded_checkpoint = checkpoint
        self.synchronous = synchronous
        self.result_queue: "queue.Queue" = queue.Queue()
        self._consumed = threading.Event()
        self._consumed.set()
        self.stop_requested = threading.Event()
        self.iteration = 0

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        if self.stop_requested.is_set():
            raise StopIteration("trial stop requested")
        self.iteration += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self.iteration)
        if self.synchronous:
            self._consumed.clear()
        self.result_queue.put((metrics, checkpoint))
        if self.synchronous:
            # back-pressure: wait until the driver polls this result so a fast
            # loop can't flood the queue (reference session does the same)
            self._consumed.wait()

    def next_result(self, timeout: Optional[float] = None):
        try:
            item = self.result_queue.get(timeout=timeout)
        except queue.Empty:
            return None
        self._consumed.set()
        return item


_session_lock = threading.Lock()
_sessions: Dict[int, _Session] = {}   # thread id -> session


def init_session(**kwargs) -> _Session:
    s = _Session(**kwargs)
    with _session_lock:
        _sessions[threading.get_ident()] = s
    return s


def shutdown_session() -> None:
    with _session_lock:
        _sessions.pop(threading.get_ident(), None)


def get_session() -> Optional[_Session]:
    with _session_lock:
        s = _sessions.get(threading.get_ident())
        if s is None and len(_sessions) == 1:
            # single-session process (worker actor): any thread may ask
            s = next(iter(_sessions.values()))
        return s


def _require_session() -> _Session:
    s = get_session()
    if s is None:
        raise RuntimeError(
            "no training session active; session.* APIs only work inside a "
            "train loop launched by a Trainer or Tuner")
    return s


# -- public API -------------------------------------------------------------

def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    if checkpoint is None:
        _require_session().report(metrics, checkpoint)
        return
    # training performance plane: handing a checkpoint to the driver
    # (serialization + the consumption handshake) is time the chip is
    # not stepping — attribute it to the ``checkpoint`` phase of the
    # open step, or the run ledger's out-of-step totals
    import time as _time
    from ray_tpu._private import step_stats
    t0 = _time.monotonic()
    try:
        _require_session().report(metrics, checkpoint)
    finally:
        step_stats.record_phase(
            "checkpoint", (_time.monotonic() - t0) * 1000.0)


def get_checkpoint() -> Optional[Checkpoint]:
    return _require_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    return _require_session().dataset_shards.get(name)


def get_world_rank() -> int:
    return _require_session().world_rank


def get_world_size() -> int:
    return _require_session().world_size


def get_local_rank() -> int:
    return _require_session().local_rank


def get_local_world_size() -> int:
    return _require_session().local_world_size


def get_node_rank() -> int:
    return _require_session().node_rank


def get_trial_name() -> str:
    return _require_session().trial_name


def get_trial_id() -> str:
    return _require_session().trial_id


def get_trial_dir() -> str:
    return _require_session().trial_dir


def get_experiment_name() -> str:
    return _require_session().experiment_name
