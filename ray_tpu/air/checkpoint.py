"""Checkpoint: the universal training-artifact currency.

Analog of /root/reference/python/ray/air/checkpoint.py:60 — a checkpoint
morphs between a dict, a directory, and bytes; here it additionally speaks
JAX: ``from_jax``/``to_jax`` store pytrees of (possibly sharded) arrays via
orbax when available, with a numpy fallback, so multi-host sharded state
round-trips without gathering to one host.
"""

from __future__ import annotations

import json
import os
import cloudpickle
import pickle
import shutil
import tarfile
import tempfile
import uuid
from typing import Any, Dict, Optional

_DICT_FILE = "checkpoint_dict.pkl"
_JAX_DIR = "jax_state"
_META_FILE = "checkpoint_meta.json"


def _new_tmpdir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu_checkpoints")
    os.makedirs(base, exist_ok=True)
    return tempfile.mkdtemp(prefix="ckpt_", dir=base)


class Checkpoint:
    """An immutable training artifact, convertible between forms.

    Construct with exactly one of ``from_dict``/``from_directory``/
    ``from_bytes``/``from_jax``; consume with the matching ``to_*``.
    Conversions are lazy and cached.
    """

    def __init__(self, *, _data: Optional[Dict[str, Any]] = None,
                 _path: Optional[str] = None):
        if (_data is None) == (_path is None):
            raise ValueError("construct via from_dict/from_directory/"
                             "from_bytes/from_jax")
        self._data = _data
        self._path = _path
        self.id = uuid.uuid4().hex[:16]

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(_data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(_path=os.path.abspath(path))

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Checkpoint":
        return cls.from_dict(pickle.loads(blob))

    @classmethod
    def from_jax(cls, state: Any,
                 metrics: Optional[Dict[str, Any]] = None) -> "Checkpoint":
        """Save a JAX pytree (TrainState, params, ...) into a directory-form
        checkpoint. Sharded ``jax.Array`` leaves are saved via orbax (ocdbt)
        when available; otherwise fully-addressable arrays fall back to a
        pickled numpy tree."""
        path = _new_tmpdir()
        save_jax_state(os.path.join(path, _JAX_DIR), state)
        with open(os.path.join(path, _META_FILE), "w") as f:
            json.dump({"metrics": metrics or {}, "format": "jax"}, f)
        return cls.from_directory(path)

    # -- converters --------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        p = os.path.join(self._path, _DICT_FILE)
        if os.path.exists(p):
            with open(p, "rb") as f:
                return pickle.load(f)
        raise ValueError(
            "directory checkpoint has no dict form; use to_directory/to_jax")

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = _new_tmpdir()
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(path) != self._path:
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        with open(os.path.join(path, _DICT_FILE), "wb") as f:
            cloudpickle.dump(self._data, f,
                             protocol=pickle.HIGHEST_PROTOCOL)
        return path

    def to_bytes(self) -> bytes:
        if self._data is not None:
            return cloudpickle.dumps(self._data,
                                 protocol=pickle.HIGHEST_PROTOCOL)
        # tar the directory into bytes (small checkpoints / tests only)
        import io
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self._path, arcname=".")
        return cloudpickle.dumps({"__dir_tar__": buf.getvalue()},
                                 protocol=pickle.HIGHEST_PROTOCOL)

    def to_jax(self, target: Any = None, *, shardings: Any = None) -> Any:
        """Restore a pytree saved with ``from_jax``. ``target`` (an abstract
        or concrete pytree) fixes the structure; ``shardings`` (tree of
        ``NamedSharding``) restores leaves already sharded over a mesh."""
        path = self._resolve_dir()
        return load_jax_state(os.path.join(path, _JAX_DIR), target,
                              shardings=shardings)

    def metrics(self) -> Dict[str, Any]:
        try:
            path = self._resolve_dir()
        except ValueError:
            return {}
        p = os.path.join(path, _META_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f).get("metrics", {})
        return {}

    def _resolve_dir(self) -> str:
        if self._path is not None:
            return self._path
        if "__dir_tar__" in (self._data or {}):
            path = _new_tmpdir()
            import io
            with tarfile.open(
                    fileobj=io.BytesIO(self._data["__dir_tar__"])) as tar:
                tar.extractall(path)
            self._path = path
            return path
        raise ValueError("dict checkpoint has no directory form")

    def __reduce__(self):
        # Checkpoints travel through the object store as bytes; directory
        # checkpoints re-materialize on the receiving host.
        return (_checkpoint_from_bytes, (self.to_bytes(),))

    def __repr__(self):
        form = "dict" if self._data is not None else f"dir:{self._path}"
        return f"Checkpoint({form})"


def _checkpoint_from_bytes(blob: bytes) -> "Checkpoint":
    return Checkpoint.from_bytes(blob)


# -- JAX pytree (de)serialization ------------------------------------------

def save_jax_state(path: str, state: Any) -> None:
    """Orbax (ocdbt, async-capable, shard-aware) when importable; else a
    pickled numpy tree (single-host only)."""
    os.makedirs(path, exist_ok=True)
    orbax_path = os.path.join(path, "orbax")
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(orbax_path, state, force=True)
        return
    except Exception:
        # a partial orbax dir must not shadow the pickle fallback at load
        shutil.rmtree(orbax_path, ignore_errors=True)
    import jax
    import numpy as np
    leaves, treedef = jax.tree.flatten(state)
    np_leaves = [np.asarray(x) if hasattr(x, "shape") else x for x in leaves]
    with open(os.path.join(path, "state.pkl"), "wb") as f:
        pickle.dump({"leaves": np_leaves, "treedef": treedef}, f,
                    protocol=pickle.HIGHEST_PROTOCOL)


def load_jax_state(path: str, target: Any = None, *,
                   shardings: Any = None) -> Any:
    import jax

    orbax_path = os.path.join(path, "orbax")
    if os.path.exists(orbax_path):
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        restore_args = None
        if shardings is not None:
            restore_args = jax.tree.map(
                lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
        restored = ckptr.restore(
            orbax_path,
            restore_args=restore_args) if restore_args is not None \
            else ckptr.restore(orbax_path)
        if target is not None:
            # orbax returns dicts; rebuild the target structure
            t_leaves, t_def = jax.tree.flatten(target)
            r_leaves = jax.tree.leaves(restored)
            if len(t_leaves) == len(r_leaves):
                return jax.tree.unflatten(t_def, r_leaves)
        return restored

    with open(os.path.join(path, "state.pkl"), "rb") as f:
        blob = pickle.load(f)
    leaves, treedef = blob["leaves"], blob["treedef"]
    if shardings is not None:
        s_leaves = jax.tree.leaves(
            shardings, is_leaf=lambda x: hasattr(x, "mesh"))
        leaves = [jax.device_put(x, s) if hasattr(x, "shape") else x
                  for x, s in zip(leaves, s_leaves)]
    restored = jax.tree.unflatten(treedef, leaves)
    if target is not None:
        t_leaves, t_def = jax.tree.flatten(target)
        r_leaves = jax.tree.leaves(restored)
        if len(t_leaves) == len(r_leaves):
            return jax.tree.unflatten(t_def, r_leaves)
    return restored
