"""Run/scaling/failure/checkpoint config dataclasses.

Analog of /root/reference/python/ray/air/config.py (ScalingConfig :79,
FailureConfig :454, CheckpointConfig :513, RunConfig :642) — extended with
TPU-mesh fields: a ScalingConfig here describes hosts x chips and the
logical device-mesh axes (data/fsdp/tensor/context/expert) the trainer
builds over them.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class ScalingConfig:
    """How many workers (host processes) and how the device mesh is laid out.

    ``num_workers`` is the number of trainer actors (one per host in a real
    TPU pod; in tests, N processes sharing a CPU platform). ``mesh_shape``
    maps logical axis name -> size; sizes must multiply to the total device
    count visible to the worker group. ``use_tpu`` reserves the host's TPU
    resource so only one group owns the chips (SURVEY.md §7 hard-part 4).
    """
    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    mesh_shape: Optional[Dict[str, int]] = None      # e.g. {"data":2,"fsdp":4}
    devices_per_worker: Optional[int] = None
    placement_strategy: str = "PACK"
    trainer_resources: Optional[Dict[str, float]] = None

    @property
    def mesh_axis_names(self) -> Tuple[str, ...]:
        if not self.mesh_shape:
            return ("data",)
        return tuple(self.mesh_shape.keys())

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", float(self.devices_per_worker or 1))
        return res

    def as_placement_group_bundles(self) -> List[Dict[str, float]]:
        return [self.worker_resources() for _ in range(self.num_workers)]


@dataclasses.dataclass
class FailureConfig:
    """Cf. reference air/config.py:454. ``max_failures=-1`` retries forever."""
    max_failures: int = 0
    fail_fast: bool = False


@dataclasses.dataclass
class CheckpointConfig:
    """Cf. reference air/config.py:513."""
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: Optional[bool] = None

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclasses.dataclass
class RunConfig:
    """Cf. reference air/config.py:642."""
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1
    log_to_file: bool = False
    callbacks: Optional[List[Any]] = None

    def __post_init__(self):
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
        if self.storage_path is None:
            self.storage_path = os.path.expanduser("~/ray_tpu_results")
