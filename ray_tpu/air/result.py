"""Result: the outcome of one training run / trial.

Analog of /root/reference/python/ray/air/result.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.air.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    log_dir: Optional[str] = None
    metrics_dataframe: Optional[Any] = None
    best_checkpoints: Optional[List[Tuple[Checkpoint, Dict[str, Any]]]] = None

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")
