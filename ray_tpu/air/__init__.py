"""AIR-style glue shared by train/tune/data/serve.

Analog of /root/reference/python/ray/air (Checkpoint checkpoint.py:60,
ScalingConfig config.py:79, FailureConfig :454, CheckpointConfig :513,
RunConfig :642, session.py:41).
"""

from ray_tpu.air.checkpoint import Checkpoint  # noqa: F401
from ray_tpu.air.config import (CheckpointConfig, FailureConfig,  # noqa: F401
                                RunConfig, ScalingConfig)
from ray_tpu.air.result import Result  # noqa: F401
from ray_tpu.air import session  # noqa: F401

__all__ = [
    "Checkpoint", "ScalingConfig", "FailureConfig", "CheckpointConfig",
    "RunConfig", "Result", "session",
]
