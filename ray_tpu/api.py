"""Public core API: init/shutdown, remote, get/put/wait, actors.

Analog of /root/reference/python/ray/_private/worker.py (init :1031,
get :2222, put :2335, wait :2391, remote :2715, shutdown :1581).
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import JobID
from ray_tpu.actor import ActorClass, get_actor, kill  # noqa: F401
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime import core_worker as cw
from ray_tpu.runtime.core_worker import (ObjectRef,  # noqa: F401
                                          ObjectRefGenerator,
                                          StreamingObjectRefGenerator)
from ray_tpu.runtime.node import NodeProcesses, new_session_dir

_init_lock = threading.Lock()
_node: Optional[NodeProcesses] = None
_pre_init_config: Optional[Dict[str, Any]] = None


def is_initialized() -> bool:
    if cw._global_worker is not None:
        return True
    from ray_tpu.util import client as client_mod
    return client_mod.current() is not None


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         system_config: Optional[Dict[str, Any]] = None,
         runtime_env: Optional[Dict[str, Any]] = None,
         namespace: str = "") -> Dict[str, Any]:
    """Start (or connect to) a cluster and attach this process as a driver.

    ``address=None`` starts a head node (GCS + raylet) owned by this driver;
    ``address="host:port"`` connects to an existing GCS.
    """
    global _node
    if address and (address.startswith("client://")
                    or address.startswith("ray://")):
        # remote-driver mode: everything proxies through the client server
        # (cf. reference ray://, python/ray/util/client/client_builder.py)
        from ray_tpu._private.logging_utils import get_logger
        from ray_tpu.util import client as client_mod
        ignored = {"num_cpus": num_cpus, "num_tpus": num_tpus,
                   "resources": resources,
                   "object_store_memory": object_store_memory,
                   "system_config": system_config,
                   "runtime_env": runtime_env,
                   "namespace": namespace or None}
        ignored = [k for k, v in ignored.items() if v]
        if ignored:
            get_logger("client").warning(
                "init(address=%r) ignores %s in remote-driver mode; "
                "configure them on the cluster / client server instead",
                address, ignored)
        ctx = client_mod.current()
        if ctx is None:
            ctx = client_mod.connect(address)
        return {"address": address, "client": ctx}
    with _init_lock:
        if is_initialized():
            return context()
        if system_config:
            # scoped to this cluster's lifetime: shutdown() restores the
            # pre-init overrides so back-to-back init/shutdown cycles (the
            # test pattern) don't leak one cluster's knobs into the next
            global _pre_init_config
            _pre_init_config = CONFIG.copy_overrides()
            CONFIG.update(system_config)

        if address is None:
            # submitted job drivers inherit their cluster from the
            # supervisor (reference RAY_ADDRESS semantics)
            import os as _os
            address = _os.environ.get("RAY_TPU_ADDRESS") or None
        if address == "auto":
            from ray_tpu.job_submission.job_manager import \
                latest_session_address
            address = latest_session_address()

        if address is None:
            session_dir = new_session_dir()
            node = NodeProcesses(session_dir)
            gcs_addr = node.start_gcs()
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            node.start_raylet(gcs_addr, resources=res or None,
                              object_store_memory=object_store_memory)
            _node = node
            raylet_addr = node.raylet_address
            node_id = node.node_id
            store_path = node.store_path
        else:
            host, port = address.rsplit(":", 1)
            gcs_addr = (host, int(port))
            # join an existing cluster: attach to a raylet on THIS host —
            # identified by its shm store segment existing locally
            import os
            from ray_tpu.runtime.gcs import GcsClient
            probe = GcsClient(gcs_addr)
            try:
                alive = [n for n in probe.call("list_nodes") if n["alive"]]
            finally:
                probe.close()
            if not alive:
                raise RuntimeError("no alive nodes in cluster")
            local = next((n for n in alive
                          if n.get("store_path")
                          and os.path.exists(n["store_path"])), None)
            if local is None:
                raise RuntimeError(
                    "no raylet running on this host; start one with "
                    "cluster.add_node() or `ray_tpu start --address=...`")
            raylet_addr = tuple(local["address"])
            node_id = local["node_id"]
            store_path = local["store_path"]
            session_dir = ""

        job_id = JobID.from_random()
        worker = cw.CoreWorker(
            mode="driver",
            gcs_address=gcs_addr,
            raylet_address=raylet_addr,
            store_path=store_path,
            node_id=node_id,
            job_id=job_id,
            session_dir=session_dir,
        )
        worker.namespace = namespace
        if CONFIG.log_to_driver:
            from ray_tpu._private.log_monitor import (LOG_CHANNEL,
                                                      print_to_driver)
            import functools as _functools
            try:
                worker.gcs.subscribe(
                    LOG_CHANNEL,
                    _functools.partial(print_to_driver,
                                       job_id=worker.job_id.hex()))
            except Exception:
                pass  # observability only; never fail init over it
        if runtime_env:
            # job-level default env, inherited by every task/actor that
            # doesn't set its own (reference job_config.runtime_env)
            worker.job_runtime_env = worker.prepare_runtime_env(runtime_env)
        job_payload = {
            "job_id": job_id.hex(),
            "driver_address": list(worker.address),
            "entrypoint": " ".join(__import__("sys").argv[:2]),
        }
        worker.gcs.call("register_job", job_payload)
        # a restarted GCS restores the job table but the fresh connection
        # has no peer identity; re-registering is idempotent and restores
        # the conn->job binding that drives job cleanup on driver exit
        worker.gcs.on_reconnect = lambda client: client.call(
            "register_job", job_payload, timeout=5)
        cw.set_global_worker(worker)
        return context()


def context() -> Dict[str, Any]:
    worker = cw.get_global_worker()
    return {
        "gcs_address": ":".join(map(str, worker.gcs._conn._sock.getpeername())),
        "node_id": worker.node_id,
        "job_id": worker.job_id.hex(),
        "session_dir": worker.session_dir,
    }


class RuntimeContext:
    """Where-am-I API for driver/task/actor code (reference
    runtime_context.py:13 ``RuntimeContext``: get_node_id/get_job_id/
    get_task_id/get_actor_id + the dict form via .get())."""

    def __init__(self, worker):
        self._worker = worker

    def get(self) -> Dict[str, Any]:
        out = {"node_id": self.node_id, "job_id": self.job_id}
        if self.task_id is not None:
            out["task_id"] = self.task_id
        if self.actor_id is not None:
            out["actor_id"] = self.actor_id
        return out

    @property
    def node_id(self) -> str:
        return self._worker.node_id

    @property
    def job_id(self) -> str:
        return self._worker.job_id.hex()

    @property
    def task_id(self) -> Optional[str]:
        tid = getattr(self._worker, "current_task_id", None)
        return tid.hex() if tid is not None else None

    @property
    def actor_id(self) -> Optional[str]:
        aid = getattr(self._worker, "current_actor_id", None)
        return aid if isinstance(aid, (str, type(None))) else aid.hex()

    # reference get_* accessors
    def get_node_id(self) -> str:
        return self.node_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_task_id(self) -> Optional[str]:
        return self.task_id

    def get_actor_id(self) -> Optional[str]:
        return self.actor_id


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(cw.get_global_worker())


def _client():
    """Active remote-driver context, if init was called with client://."""
    from ray_tpu.util import client as client_mod
    return client_mod.current()


def shutdown() -> None:
    global _node, _pre_init_config
    from ray_tpu.util import client as client_mod
    if client_mod.current() is not None:
        client_mod.disconnect()
        return
    with _init_lock:
        worker = cw._global_worker
        if worker is not None:
            try:
                worker.gcs.call("finish_job", {"job_id": worker.job_id.hex()},
                                timeout=5)
            except Exception:
                pass
            worker.shutdown()
            cw.set_global_worker(None)
        if _node is not None:
            _node.stop()
            _node = None
        if _pre_init_config is not None:
            CONFIG.set_overrides(_pre_init_config)
            _pre_init_config = None


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` decorator."""
    def decorate(target):
        if inspect.isclass(target):
            return ActorClass(
                target,
                num_cpus=kwargs.get("num_cpus", 1.0),
                num_tpus=kwargs.get("num_tpus", 0.0),
                resources=kwargs.get("resources"),
                max_restarts=kwargs.get("max_restarts", 0),
                name=kwargs.get("name"),
                namespace=kwargs.get("namespace", ""),
                lifetime=kwargs.get("lifetime"),
                max_concurrency=kwargs.get("max_concurrency"),
                concurrency_groups=kwargs.get("concurrency_groups"),
                scheduling_strategy=kwargs.get("scheduling_strategy"),
                runtime_env=kwargs.get("runtime_env"))
        return RemoteFunction(
            target,
            num_returns=kwargs.get("num_returns", 1),
            num_cpus=kwargs.get("num_cpus", 1.0),
            num_tpus=kwargs.get("num_tpus", 0.0),
            resources=kwargs.get("resources"),
            max_retries=kwargs.get("max_retries", 3),
            scheduling_strategy=kwargs.get("scheduling_strategy"),
            runtime_env=kwargs.get("runtime_env"))

    if len(args) == 1 and not kwargs and callable(args[0]):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword arguments only")
    return decorate


def put(value: Any) -> ObjectRef:
    ctx = _client()
    if ctx is not None:
        return ctx.put(value)
    return cw.get_global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    ctx = _client()
    if ctx is not None:
        return ctx.get(refs, timeout=timeout)
    worker = cw.get_global_worker()
    if isinstance(refs, ObjectRef):
        return worker.get([refs], timeout=timeout)[0]
    return worker.get(list(refs), timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None,
         fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    ctx = _client()
    if ctx is not None:
        return ctx.wait(refs, num_returns=num_returns, timeout=timeout)
    return cw.get_global_worker().wait(
        list(refs), num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local)


def nodes() -> List[dict]:
    ctx = _client()
    if ctx is not None:
        return ctx.nodes()
    return cw.get_global_worker().gcs.call("list_nodes")


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for r, v in n["resources"].items():
                total[r] = total.get(r, 0) + v
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for r, v in n["available"].items():
                total[r] = total.get(r, 0) + v
    return total
