"""Logical-axis → mesh-axis sharding rules (GSPMD annotation layer).

Models annotate parameters/activations with *logical* axis names ("embed",
"mlp", "heads", "batch", "seq", ...); this module maps them onto physical mesh
axes and produces ``NamedSharding``s for ``jax.jit``'s in/out shardings.  One
rule table expresses DP, ZeRO-3/FSDP, tensor parallelism, sequence/context
parallelism, and expert parallelism — the strategies the reference either
delegates to torch (DP/DDP: /root/reference/python/ray/train/torch/
train_loop_utils.py ``prepare_model``) or lacks entirely (TP/SP/EP —
SURVEY.md §2.6).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MeshAxes = Union[None, str, Tuple[str, ...]]

# Default rule table (t5x-style).  batch shards over data+fsdp (ZeRO data
# axes); params shard over fsdp (ZeRO-3) and tensor; sequence activations
# shard over context for ring attention; experts over an expert dimension
# folded into data.
DEFAULT_RULES: Tuple[Tuple[str, MeshAxes], ...] = (
    ("batch", ("data", "fsdp")),
    ("seq", "context"),            # activation sequence axis (context parallel)
    ("kv_seq", None),              # gathered KV sequence stays replicated
    ("act_embed", None),           # activation model dim (params shard fsdp,
                                   # activations stay whole per shard)
    ("act_vocab", "tensor"),       # logits vocab dim shards with TP
    ("embed", "fsdp"),             # ZeRO-3 parameter shard axis
    ("mlp", "tensor"),
    ("heads", "tensor"),
    ("heads_embed", "tensor"),     # merged heads*head_dim axis (attn output proj)
    ("kv", "tensor"),              # kv heads shard with TP (GQA: kv_heads >= tp)
    ("head_dim", None),
    ("vocab", "tensor"),
    ("expert", ("data", "fsdp")),  # expert-parallel: experts across data axes
    ("expert_in", None),           # expert weight model dim (expert axis
                                   # already consumes the data axes)
    ("expert_mlp", "tensor"),
    ("stage", None),               # pipeline stage axis (pipeline.py overrides)
    ("norm", None),
    ("conv_io", None),
    ("conv_spatial", None),
)


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    rules: Tuple[Tuple[str, MeshAxes], ...] = DEFAULT_RULES

    def to_mesh_axes(self, logical: str, mesh: Optional[Mesh] = None) -> MeshAxes:
        for name, axes in self.rules:
            if name == logical:
                return self._prune(axes, mesh)
        return None

    @staticmethod
    def _prune(axes: MeshAxes, mesh: Optional[Mesh]) -> MeshAxes:
        """Drop mesh axes that don't exist / are size 1 on this mesh."""
        if axes is None or mesh is None:
            return axes
        shape = dict(mesh.shape)
        if isinstance(axes, str):
            return axes if shape.get(axes, 1) > 1 else None
        kept = tuple(a for a in axes if shape.get(a, 1) > 1)
        return kept if len(kept) > 1 else (kept[0] if kept else None)

    def replace(self, **overrides: MeshAxes) -> "ShardingRules":
        new = [(n, overrides.get(n, a)) for n, a in self.rules]
        for extra in overrides.keys() - {n for n, _ in self.rules}:
            new.append((extra, overrides[extra]))
        return ShardingRules(tuple(new))


LOGICAL_RULES = ShardingRules()


def logical_spec(logical_axes: Sequence[Optional[str]],
                 mesh: Optional[Mesh] = None,
                 rules: ShardingRules = LOGICAL_RULES) -> PartitionSpec:
    """('batch','seq','embed') -> PartitionSpec(('data','fsdp'),'context','fsdp')."""
    return PartitionSpec(*[
        rules.to_mesh_axes(a, mesh) if a is not None else None
        for a in logical_axes])


def logical_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                     rules: ShardingRules = LOGICAL_RULES) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, mesh, rules))


def with_sharding(mesh: Mesh, x: jax.Array,
                  logical_axes: Sequence[Optional[str]],
                  rules: ShardingRules = LOGICAL_RULES) -> jax.Array:
    """Constrain an intermediate value's sharding inside jit."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules))


def logical_pspec_to_mesh(spec: Optional[PartitionSpec], mesh: Mesh,
                          rules: ShardingRules = LOGICAL_RULES) -> NamedSharding:
    """Translate a PartitionSpec of *logical* names (e.g. from
    ``nn.get_partition_spec``) into a mesh NamedSharding."""
    if spec is None:
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh, PartitionSpec(*[
        rules.to_mesh_axes(a, mesh) if a is not None else None for a in spec]))


def tree_mesh_shardings(logical_spec_tree: Any, mesh: Mesh,
                        rules: ShardingRules = LOGICAL_RULES) -> Any:
    """Map ``logical_pspec_to_mesh`` over a tree of logical PartitionSpecs
    (None leaves -> replicated)."""
    return jax.tree.map(
        lambda s: logical_pspec_to_mesh(s, mesh, rules),
        logical_spec_tree,
        is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))


def tree_logical_axes(params: Any) -> Any:
    """Extract logical axis metadata from a flax param tree
    (``nn.Partitioned`` leaves from ``with_logical_partitioning``)."""
    import flax.linen as nn

    def leaf_axes(p):
        if isinstance(p, nn.Partitioned):
            return p.names
        return None

    return jax.tree.map(leaf_axes, params,
                        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def shard_pytree_like(tree: Any, axes_tree: Any, mesh: Mesh,
                      rules: ShardingRules = LOGICAL_RULES) -> Any:
    """NamedShardings for a pytree given a matching tree of logical axes.

    ``axes_tree`` leaves are tuples of logical axis names (or None for fully
    replicated); tuples/None are leaves here, so the two trees are flattened
    independently and zipped.
    """
    leaves, treedef = jax.tree.flatten(tree)
    axes_leaves = treedef.flatten_up_to(axes_tree)
    if len(axes_leaves) != len(leaves):
        raise ValueError("axes_tree structure does not match tree")

    def one(axes):
        if axes is None:
            return NamedSharding(mesh, PartitionSpec())
        return logical_sharding(mesh, axes, rules)

    return jax.tree.unflatten(treedef, [one(a) for a in axes_leaves])
