"""Device-mesh construction for single- and multi-host TPU topologies.

The mesh is the framework's unit of ML parallelism: axes ``data`` (DP),
``fsdp`` (ZeRO-3 parameter sharding), ``context`` (sequence/context
parallelism for ring attention), and ``tensor`` (megatron-style TP).  The
reference has no analog — its DP is torch DDP over NCCL
(/root/reference/python/ray/train/torch/config.py:29) and TP/SP/EP are absent
(SURVEY.md §2.6); here they are all layouts of one mesh, and XLA emits the
collectives.

Axis order matters on hardware: later mesh axes map to faster ICI dimensions
under ``mesh_utils.create_device_mesh``, so ``tensor`` (highest-traffic
collectives) is innermost.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_ORDER: Tuple[str, ...] = ("stage", "data", "fsdp", "context", "tensor")


@dataclasses.dataclass
class MeshConfig:
    """Declarative mesh request, resolved against available devices.

    Sizes of ``-1`` mean "absorb remaining devices" (at most one axis may be
    -1).  This is the TPU analog of the reference's ``ScalingConfig``
    (/root/reference/python/ray/air/config.py:79): instead of
    num_workers×use_gpu it declares how chips factor into parallelism axes.
    """

    data: int = -1
    fsdp: int = 1
    context: int = 1
    tensor: int = 1
    stage: int = 1                 # pipeline stages (outermost: slowest links)

    def sizes(self) -> Tuple[int, ...]:
        return (self.stage, self.data, self.fsdp, self.context, self.tensor)

    def resolve(self, n_devices: int) -> Tuple[int, ...]:
        sizes = list(self.sizes())
        wildcard = [i for i, s in enumerate(sizes) if s == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if wildcard:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcard[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {tuple(sizes)} needs {fixed} devices, have {n_devices}")
        return tuple(sizes)


def mesh_shape_for(n_devices: int, config: Optional[MeshConfig] = None
                   ) -> Tuple[int, ...]:
    return (config or MeshConfig()).resolve(n_devices)


def build_mesh(config: Optional[MeshConfig] = None,
               devices: Optional[Sequence[jax.Device]] = None,
               axis_names: Tuple[str, ...] = AXIS_ORDER) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) with ICI-aware placement.

    Uses ``mesh_utils.create_device_mesh`` when the devices span a real TPU
    topology so physically-adjacent chips land on the innermost (highest
    traffic) axes; falls back to a plain reshape for host-platform devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = mesh_shape_for(len(devices), config)
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True)
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def local_mesh(axis: str = "data") -> Mesh:
    """1-axis mesh over this process's addressable devices (single-host DP)."""
    devices = jax.local_devices()
    return Mesh(np.asarray(devices), (axis,))


def dp_size(mesh: Mesh) -> int:
    """Global batch-sharding factor (data × fsdp; batch shards over both)."""
    return mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
