"""SPMD pipeline parallelism: GPipe microbatching over a ``stage`` mesh axis.

The reference has no pipeline engine (SURVEY.md §2.6: "Pipeline parallel:
absent").  TPU-first design: instead of stage *processes* exchanging
activations over a network (the GPU/NCCL shape of PP), every device runs the
same compiled program under ``shard_map``; layer parameters are sharded over
the ``stage`` axis (each stage holds L/n_stages layers), and activations hop
stage→stage via ``jax.lax.ppermute`` on ICI/DCN inside one ``lax.scan`` —
the classic weight-stationary SPMD pipeline.  The whole loop is
differentiable, so the backward pipeline (reverse ppermute order) falls out
of autodiff; no 1F1B scheduler to hand-write.

Schedule: plain GPipe.  ``n_microbatches`` chunks flow through
``n_stages + n_microbatches - 1`` ticks; bubble fraction is
``(n_stages-1)/(n_stages+n_microbatches-1)`` — pick microbatches ≥ 4× stages
to amortize.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# shard_map promotion shim (_shard_map vs jax.experimental.shard_map)
from ray_tpu._private.jax_compat import shard_map as _shard_map


def _stage_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def spmd_pipeline(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  x: jax.Array,
                  *,
                  mesh: Mesh,
                  n_microbatches: int,
                  axis_name: str = "stage",
                  batch_axes=("data", "fsdp")) -> jax.Array:
    """Run ``stage_fn`` as an ``n_stages``-deep pipeline over microbatches.

    stage_params: pytree whose leaves have leading dim ``n_stages`` (stage i
    holds slice i); sharded over ``axis_name`` by this wrapper.
    x: global [B, ...] batch; B must divide into ``n_microbatches``.
    stage_fn(params_slice, microbatch) -> microbatch-shaped output; applied
    once per stage, so a transformer's blocks stack as
    [n_stages, layers_per_stage, ...] with an inner scan in ``stage_fn``.
    """
    n_stages = mesh.shape[axis_name]
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible into {n_microbatches} "
                         "microbatches")
    mb = b // n_microbatches
    xs = x.reshape(n_microbatches, mb, *x.shape[1:])

    batch_axes = tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None
    x_spec = P(None, batch_axes, *([None] * (x.ndim - 1)))
    param_specs = jax.tree.map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stage_params)
    perm = _stage_perm(n_stages)

    def local(params, xs_local):
        # leading stage dim is length-1 locally; peel it off
        params = jax.tree.map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis_name)
        m = xs_local.shape[0]
        state0 = jnp.zeros_like(xs_local[0])
        out0 = jnp.zeros_like(xs_local)

        def tick(carry, t):
            state, outbuf = carry
            # stage 0 ingests microbatch t (clamped; masked-out when t >= m)
            feed = xs_local[jnp.clip(t, 0, m - 1)]
            state = jnp.where(idx == 0, feed, state)
            out = stage_fn(params, state)
            # the last stage finished microbatch t-(n_stages-1) this tick
            done = t - (n_stages - 1)
            write = jnp.logical_and(idx == n_stages - 1, done >= 0)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf,
                jnp.where(write, out,
                          jax.lax.dynamic_index_in_dim(
                              outbuf, jnp.clip(done, 0, m - 1), 0,
                              keepdims=False)),
                jnp.clip(done, 0, m - 1), 0)
            state = jax.lax.ppermute(out, axis_name, perm)
            return (state, outbuf), None

        (_, outbuf), _ = jax.lax.scan(
            tick, (state0, out0), jnp.arange(m + n_stages - 1))
        # only the last stage's buffer is real; broadcast it to every stage
        # so the out_spec can treat the result as stage-replicated
        outbuf = jnp.where(idx == n_stages - 1, outbuf, jnp.zeros_like(outbuf))
        return jax.lax.psum(outbuf, axis_name)

    out = _shard_map(local, mesh=mesh,
                        in_specs=(param_specs, x_spec),
                        out_specs=x_spec, check_vma=False)(stage_params, xs)
    return out.reshape(b, *out.shape[2:])


def pipelined_lm_forward(cfg, mesh: Mesh, variables: Any, tokens: jax.Array,
                         *, n_microbatches: int, rules=None) -> jax.Array:
    """GPT forward with the block stack pipelined over the ``stage`` axis.

    Reuses the GPT modules functionally: embedding and head run replicated
    across stages (they shard over fsdp/tensor as usual); the scanned block
    params [L, ...] are regrouped to [n_stages, L/n_stages, ...] and each
    stage scans its local layers.  Requires ``cfg.scan_layers`` (stacked
    block params) and ``cfg.n_layers % n_stages == 0``.
    """
    import flax.linen as nn
    from ray_tpu.models.gpt import GPT, Block, RMSNorm
    from ray_tpu.ops.layers import rope_frequencies
    from ray_tpu.parallel.sharding import LOGICAL_RULES

    rules = rules or LOGICAL_RULES
    n_stages = mesh.shape.get("stage", 1)
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible into "
                         f"{n_stages} stages")
    if not cfg.scan_layers:
        raise ValueError("pipelining needs scan_layers=True (stacked params)")
    params = nn.meta.unbox(variables["params"])
    block_params = params["blocks"]
    per_stage = cfg.n_layers // n_stages
    staged = jax.tree.map(
        lambda p: p.reshape(n_stages, per_stage, *p.shape[1:]), block_params)

    embed = params["embed"]
    x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    block = Block(cfg, mesh=None, rules=rules)

    def stage_fn(stage_p, h):
        def layer(carry, p):
            return block.apply({"params": p}, carry, cos, sin), None
        h, _ = jax.lax.scan(layer, h, stage_p)
        return h

    x = spmd_pipeline(stage_fn, staged, x, mesh=mesh,
                      n_microbatches=n_microbatches)

    x = RMSNorm(cfg.norm_eps).apply({"params": params["final_norm"]}, x)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
    else:
        head = params["lm_head"]["kernel"]
        logits = jnp.einsum("bsd,dv->bsv", x, head.astype(cfg.dtype))
    return logits.astype(jnp.float32)
