"""Parallelism layer: device meshes, logical-axis sharding rules, collectives.

This is the TPU-native replacement for the reference's ML-parallelism surface
(SURVEY.md §2.6): DP/FSDP/TP/SP/EP are all expressed as shardings of one
``jax.sharding.Mesh`` and compiled into XLA collectives over ICI/DCN, instead
of NCCL process groups (/root/reference/python/ray/util/collective/) and
per-framework DDP wrappers (/root/reference/python/ray/train/torch/config.py:29).
"""

from ray_tpu.parallel.mesh import (MeshConfig, build_mesh, local_mesh,
                                   mesh_shape_for)
from ray_tpu.parallel.pipeline import pipelined_lm_forward, spmd_pipeline
from ray_tpu.parallel.sharding import (LOGICAL_RULES, ShardingRules,
                                       logical_sharding, logical_spec,
                                       shard_pytree_like, with_sharding)

__all__ = [
    "MeshConfig", "build_mesh", "local_mesh", "mesh_shape_for",
    "ShardingRules", "LOGICAL_RULES", "logical_spec", "logical_sharding",
    "with_sharding", "shard_pytree_like", "spmd_pipeline",
    "pipelined_lm_forward",
]
