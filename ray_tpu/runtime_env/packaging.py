"""Runtime-env package handling: zip local code, content-address it in the
GCS KV store, unpack once per node.

Analog of /root/reference/python/ray/_private/runtime_env/packaging.py
(URI packaging + cache) — the store is the GCS internal KV (the reference
uploads there too for small packages) and unpack is rename-atomic so many
workers racing on one node do the work once.
"""

from __future__ import annotations

import hashlib
import io
import os
import shutil
import tempfile
import zipfile
from typing import List, Optional

_KV_PREFIX = "rtenv_pkg:"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 512 * 1024 * 1024


def tree_fingerprint(path: str) -> str:
    """Cheap change detector: hash of (relpath, mtime_ns, size) for every
    file — used to invalidate the driver's prepare cache without zipping."""
    h = hashlib.sha256()
    if os.path.isfile(path):
        st = os.stat(path)
        h.update(f"{path}:{st.st_mtime_ns}:{st.st_size}".encode())
    else:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for f in sorted(files):
                full = os.path.join(root, f)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                rel = os.path.relpath(full, path)
                h.update(f"{rel}:{st.st_mtime_ns}:{st.st_size}".encode())
    return h.hexdigest()[:16]


def zip_directory(path: str) -> bytes:
    """Deterministic zip of a directory tree (or a single .py file)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            entries = []
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
                for f in sorted(files):
                    full = os.path.join(root, f)
                    entries.append((full, os.path.relpath(full, path)))
            for full, rel in entries:
                zf.write(full, rel)
    blob = buf.getvalue()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(blob)} bytes "
            f"(limit {MAX_PACKAGE_BYTES}); ship big data via the object "
            "store or a filesystem, not runtime_env")
    return blob


def package_uri(blob: bytes) -> str:
    return "pkg://" + hashlib.sha256(blob).hexdigest()[:32]


def upload_package(gcs, path: str) -> str:
    """Zip + upload to the GCS KV; returns the content-addressed URI."""
    blob = zip_directory(path)
    uri = package_uri(blob)
    gcs.kv_put(_KV_PREFIX + uri, blob, overwrite=False)
    return uri


def ensure_local(gcs, uri: str, base_dir: str) -> str:
    """Download+unpack `uri` under base_dir (idempotent, rename-atomic)."""
    dest = os.path.join(base_dir, uri.replace("pkg://", ""))
    if os.path.isdir(dest):
        return dest
    blob = gcs.kv_get(_KV_PREFIX + uri)
    if blob is None:
        raise FileNotFoundError(f"runtime_env package {uri} not in GCS")
    os.makedirs(base_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=base_dir, prefix=".unpack-")
    try:
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            if not os.path.isdir(dest):  # lost a benign unpack race?
                raise
            shutil.rmtree(tmp, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dest
