"""Per-runtime-env pip isolation: one cached venv per requirement set.

Analog of /root/reference/python/ray/_private/runtime_env/pip.py:75
(``PipProcessor``) + the URI cache (uri_cache.py): a runtime env with
``pip: [...]`` gets a virtualenv keyed by the hash of its sorted
requirements; the raylet launches that env's workers with the venv's
interpreter, so two tasks can hold conflicting package versions
concurrently.  Venvs are created with ``--system-site-packages`` (the
worker still sees the baked image: jax, numpy, ray_tpu via PYTHONPATH)
and reused across workers/jobs until the cache dir is cleared.

Zero-egress seam: ``runtime_env_pip_find_links`` points pip at a local
wheelhouse with ``--no-index`` — the test builds tiny wheels by hand —
while production hosts with egress just leave it unset.
"""

from __future__ import annotations

import fcntl
import hashlib
import logging
import os
import re
import subprocess
import sys
from typing import List

from ray_tpu._private.config import CONFIG

logger = logging.getLogger(__name__)


def env_key(requirements: List[str]) -> str:
    """Cache key: requirements + the wheelhouse + the base interpreter —
    changing any of them must produce a fresh venv, not reuse a stale
    one built against different inputs."""
    blob = "\n".join(sorted(requirements)
                     + [f"find_links={CONFIG.runtime_env_pip_find_links}",
                        f"base={sys.executable}"]).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _cache_dir() -> str:
    if CONFIG.runtime_env_cache_dir:
        return CONFIG.runtime_env_cache_dir
    # per-uid dir, created 0700: a shared /tmp cache would let another
    # local user pre-plant an interpreter for a predictable key
    return f"/tmp/ray_tpu_runtime_envs_{os.getuid()}"


def venv_site_packages(python: str) -> str:
    """Site-packages dir of a venv given its interpreter path."""
    root = os.path.dirname(os.path.dirname(python))
    return os.path.join(
        root, "lib",
        f"python{sys.version_info[0]}.{sys.version_info[1]}",
        "site-packages")


def ensure_pip_env(requirements: List[str],
                   timeout_s: float = 300.0) -> str:
    """Create (or reuse) the venv for these requirements; returns the
    path of its python interpreter.  Safe under concurrent callers from
    multiple raylet processes (file lock + ready marker)."""
    key = env_key(requirements)
    root = os.path.join(_cache_dir(), f"pip_{key}")
    py = os.path.join(root, "bin", "python")
    ready = os.path.join(root, ".ready")
    if os.path.exists(ready):
        return py
    os.makedirs(_cache_dir(), mode=0o700, exist_ok=True)
    lock_path = os.path.join(_cache_dir(), f"pip_{key}.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):   # lost the race: someone built it
                return py
            logger.info("creating pip runtime env %s: %s",
                        key, requirements)
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 root],
                check=True, capture_output=True, timeout=timeout_s)
            # When this process itself runs inside a venv (the baked
            # image ships one), --system-site-packages points at the
            # BASE python, not our venv — chain our site-packages via a
            # .pth so workers still see jax/numpy/cloudpickle.  Installed
            # requirement dirs sort before the .pth's appended paths, so
            # pinned versions still shadow the parent's copies.
            import site
            parent_sites = [p for p in site.getsitepackages()
                            if os.path.isdir(p)]
            vs = venv_site_packages(py)
            with open(os.path.join(vs, "_parent_site.pth"), "w") as f:
                f.write("\n".join(p for p in parent_sites
                                  if os.path.abspath(p)
                                  != os.path.abspath(vs)) + "\n")
            cmd = [py, "-m", "pip", "install", "--quiet",
                   "--disable-pip-version-check"]
            find_links = CONFIG.runtime_env_pip_find_links
            if find_links:
                cmd += ["--no-index", "--find-links", find_links]
            cmd += list(requirements)
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout_s)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install failed for runtime env "
                    f"{requirements}:\n{proc.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write("\n".join(sorted(requirements)))
            return py
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def normalize_conda_field(conda) -> List[str]:
    """Translate a conda runtime env into pip requirements for the venv
    machinery (reference conda plugin: _private/runtime_env/conda.py).

    Hermetic TPU images ship no conda binary, so instead of a solver we
    honor the *declarative content* of the common shapes:

    * dict (environment.yml content): ``dependencies`` entries become
      pip requirements — ``name=ver`` → ``name==ver``, the nested
      ``{"pip": [...]}`` block passes through; ``python=...``/``pip``
      entries are skipped (the interpreter is the image's own).
    * path to an ``environment.yml``: parsed the same way.
    * named conda env (bare string): rejected — there is no conda
      installation to look it up in.
    """
    if isinstance(conda, str):
        if conda.endswith((".yml", ".yaml")):
            import yaml
            with open(conda) as f:
                conda = yaml.safe_load(f) or {}
        else:
            raise ValueError(
                f"conda env by name ({conda!r}) is not supported: images "
                "are hermetic (no conda installation to resolve it). "
                "Pass the environment.yml content (dict or path) — its "
                "dependencies run in an isolated venv — or use 'pip'.")
    if not isinstance(conda, dict):
        raise TypeError("runtime_env 'conda' must be an environment.yml "
                        "dict, a path to one, or a named env")
    reqs: List[str] = []
    for dep in conda.get("dependencies", []):
        if isinstance(dep, dict):
            reqs.extend(dep.get("pip", []))
            continue
        if not isinstance(dep, str):
            raise TypeError(f"bad conda dependency: {dep!r}")
        dep = dep.strip()
        name = re.split(r"[=<>!~ ]", dep, maxsplit=1)[0]
        if name in ("python", "pip"):
            continue  # interpreter/installer come from the image
        # conda-only pin forms → pip:
        #   name=1.2           -> name==1.2
        #   name=1.2=py39h...  -> name==1.2  (conda env export emits
        #                         build strings pip cannot parse)
        m = re.fullmatch(r"([A-Za-z0-9_.\-]+)=([^=<>!~]+)(=[^=]+)?", dep)
        if m:
            dep = f"{m.group(1)}=={m.group(2)}"
        reqs.append(dep)
    return sorted(reqs)


def normalize_pip_field(pip) -> List[str]:
    """Accept the reference's shapes: list of requirement strings or
    {"packages": [...]} (pip.py RuntimeEnvPlugin validation)."""
    if isinstance(pip, dict):
        pip = pip.get("packages", [])
    if not isinstance(pip, (list, tuple)) or \
            not all(isinstance(r, str) for r in pip):
        raise TypeError(
            "runtime_env 'pip' must be a list of requirement strings "
            "or {'packages': [...]}")
    return sorted(pip)
