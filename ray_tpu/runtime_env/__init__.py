"""Runtime environments (SURVEY.md §2.3 runtime_env row).

Analog of /root/reference/python/ray/runtime_env/ (RuntimeEnv :515) +
_private/runtime_env/ (packaging, uri_cache, plugins).
"""

from ray_tpu.runtime_env.runtime_env import (  # noqa: F401
    RuntimeEnv, prepare_runtime_env, setup_runtime_env)

__all__ = ["RuntimeEnv", "prepare_runtime_env", "setup_runtime_env"]
