"""Container runtime_env: run workers inside a container image.

Analog of /root/reference/python/ray/_private/runtime_env/container.py
(ContainerManager.setup): the descriptor
``runtime_env={"container": {"image": ..., "run_options": [...],
"driver": "podman"}}`` turns a worker spawn command into
``podman run <mounts/namespaces> --entrypoint python <image> <args>``.
The container shares the host's network/pid/ipc namespaces and mounts
the session dir and the shm store segment, so the containerized worker
speaks to the raylet and maps the object store exactly like a host
worker — isolation covers the filesystem/interpreter, not the cluster
fabric (the reference's model).

This image ships no podman/docker, so end-to-end tests drive a
recording fake driver (tests/test_runtime_env.py); command construction
is pure and fully covered either way.  Containerized workers always
exec (a fork off the warm zygote cannot enter an image).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Dict, List, Optional


class ContainerError(ValueError):
    pass


def validate(container: dict) -> dict:
    if not isinstance(container, dict) or not container.get("image"):
        raise ContainerError(
            'runtime_env["container"] needs an "image"; got '
            f"{container!r}")
    driver = container.get("driver", "podman")
    opts = container.get("run_options", [])
    if not isinstance(opts, (list, tuple)) or \
            not all(isinstance(o, str) for o in opts):
        raise ContainerError("container run_options must be a list of "
                             "strings")
    return {"image": container["image"], "driver": driver,
            "run_options": list(opts)}


def driver_path(container: dict) -> Optional[str]:
    """Resolved container runtime executable, or None if absent."""
    d = validate(container)["driver"]
    if os.path.sep in d:
        return d if os.access(d, os.X_OK) else None
    return shutil.which(d)


def wrap_worker_command(container: dict, cmd: List[str], *,
                        session_dir: str, store_path: str,
                        env: Dict[str, str]) -> List[str]:
    """[driver run ... --entrypoint python image <worker args>].

    ``cmd`` is the host spawn command ([python, -m, module, flags...]);
    inside the image the interpreter is whatever ``python`` resolves to
    there.  Mounts: the session dir (logs, sockets, spill) and the shm
    store segment's directory (the worker mmaps the segment by path).
    Critical env rides as explicit --env so it works across drivers
    (podman's --env-host would leak the whole host env; the reference
    uses it, we pass the system allowlist plus the user's runtime_env
    env_vars keys)."""
    c = validate(container)
    drv = driver_path(c)
    if drv is None:
        raise ContainerError(
            f"container runtime {c['driver']!r} not found on this host "
            "(install podman/docker or point 'driver' at an executable)")
    store_dir = os.path.dirname(store_path) or "/"
    out = [drv, "run", "--rm",
           "-v", f"{session_dir}:{session_dir}",
           "-v", f"{store_dir}:{store_dir}",
           "--network=host", "--pid=host", "--ipc=host"]
    forward = ["PYTHONPATH", "RAY_TPU_SYSTEM_CONFIG",
               "RAY_TPU_RUNTIME_ENV", "RAY_TPU_INLINE_OBJECT_MAX_BYTES",
               "JAX_PLATFORMS", "XLA_FLAGS"]
    # user env_vars from the runtime_env descriptor ride along too —
    # the raylet merged them into `env`, and the descriptor JSON names
    # which keys are the user's (the reference forwards the entire host
    # env via --env-host; we forward system allowlist + user keys)
    user_keys: set = set()
    renv_json = env.get("RAY_TPU_RUNTIME_ENV")
    if renv_json:
        try:
            user_keys = set(json.loads(renv_json).get("env_vars") or {})
            forward += [k for k in user_keys if k not in forward]
        except ValueError:
            pass
    for key in forward:
        # user keys forward even when empty (blanking an image-baked
        # var is a legitimate override); system keys only when set
        if key in user_keys and key in env:
            out += ["--env", f"{key}={env[key]}"]
        elif key not in user_keys and env.get(key):
            out += ["--env", f"{key}={env[key]}"]
    out += list(c["run_options"])
    out += ["--entrypoint", "python", c["image"]]
    out += cmd[1:]                       # drop the host interpreter path
    return out
