"""RuntimeEnv: per-job/task/actor execution environment.

Analog of /root/reference/python/ray/runtime_env/runtime_env.py (RuntimeEnv
class) + _private/runtime_env/ plugins. TPU-native scope: env_vars,
working_dir, and py_modules ship code/config through the GCS KV; ``pip``
gives CPU-side workers per-env dependency isolation via cached local
venvs (runtime_env/pip.py — TPU-pod images should still bake heavy deps);
``conda`` environment.yml specs fold into the same venv path (their
dependencies become pip requirements; no conda binary in hermetic
images, so named envs are rejected).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any, Dict, List, Optional

from ray_tpu.runtime_env import packaging

_ALLOWED = {"env_vars", "working_dir", "py_modules", "pip", "conda",
            "container", "config"}


class RuntimeEnv(dict):
    """Validated dict describing a worker environment.

    >>> RuntimeEnv(env_vars={"TOKENIZERS_PARALLELISM": "false"},
    ...            working_dir="./src")
    """

    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Any = None, conda: Any = None,
                 container: Optional[Dict[str, Any]] = None,
                 config: Optional[Dict[str, Any]] = None):
        super().__init__()
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            self["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.exists(working_dir):
                raise ValueError(f"working_dir {working_dir!r} not found")
            self["working_dir"] = working_dir
        if py_modules:
            for m in py_modules:
                if not os.path.exists(m):
                    raise ValueError(f"py_module {m!r} not found")
            self["py_modules"] = list(py_modules)
        if pip:
            from ray_tpu.runtime_env.pip import normalize_pip_field
            self["pip"] = normalize_pip_field(pip)
        if container:
            from ray_tpu.runtime_env.container import validate
            self["container"] = validate(container)
        if conda:
            # conda specs fold into the same venv isolation path as pip:
            # the environment.yml's dependencies become requirements (the
            # image's interpreter replaces conda's python solver)
            from ray_tpu.runtime_env.pip import normalize_conda_field
            merged = sorted(set(self.get("pip", []))
                            | set(normalize_conda_field(conda)))
            if merged:
                self["pip"] = merged
        if config:
            self["config"] = dict(config)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RuntimeEnv":
        unknown = set(d) - _ALLOWED
        if unknown:
            raise ValueError(f"unknown runtime_env field(s): {sorted(unknown)}")
        return cls(**{k: v for k, v in d.items()})

    def to_dict(self) -> Dict[str, Any]:
        return dict(self)


def prepare_runtime_env(raw: Optional[Dict[str, Any]], gcs
                        ) -> Optional[Dict[str, Any]]:
    """Driver side: package+upload local paths; returns the wire descriptor
    sent with lease requests / actor specs (the reference's serialized
    RuntimeEnvInfo)."""
    if not raw:
        return None
    env = raw if isinstance(raw, RuntimeEnv) else RuntimeEnv.from_dict(raw)
    desc: Dict[str, Any] = {}
    if env.get("env_vars"):
        desc["env_vars"] = dict(env["env_vars"])
    if env.get("working_dir"):
        desc["working_dir"] = packaging.upload_package(
            gcs, env["working_dir"])
    if env.get("py_modules"):
        desc["py_modules"] = [packaging.upload_package(gcs, m)
                              for m in env["py_modules"]]
    if env.get("pip"):
        desc["pip"] = list(env["pip"])
    if env.get("container"):
        desc["container"] = dict(env["container"])
    if env.get("config"):
        desc["config"] = dict(env["config"])
    if not desc:
        return None
    desc["hash"] = hashlib.sha256(
        json.dumps(desc, sort_keys=True).encode()).hexdigest()[:16]
    return desc


def setup_runtime_env(desc: Dict[str, Any], gcs, session_dir: str) -> None:
    """Worker side: apply a descriptor before running any user code."""
    base = os.path.join(session_dir or ".", "runtime_env")
    for uri in desc.get("py_modules", []):
        path = packaging.ensure_local(gcs, uri, base)
        if path not in sys.path:
            sys.path.insert(0, path)
    if desc.get("working_dir"):
        path = packaging.ensure_local(gcs, desc["working_dir"], base)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
    for k, v in desc.get("env_vars", {}).items():
        os.environ[k] = v
