"""Telemetry overhead guard: task throughput with RAY_TPU_TELEMETRY=0/1.

The always-on runtime telemetry (_private/runtime_metrics.py) claims a
record path cheap enough to leave on in production.  This bench holds it
to that: the small-task sync throughput loop (the single most
instrument-dense path — RPC dispatch, submit, push batch, e2e latency,
execution timing all fire per task) runs in fresh subprocesses with the
kill switch off and on, A/B **interleaved** on the same box so the
VM-throttle drift this host suffers hits both arms equally.  The
``telemetry`` MICROBENCH section records both rates and the delta; the
acceptance bar is <= 3% overhead for telemetry on.

Usage:
    python benchmarks/telemetry_overhead.py            # full A/B, JSON rows
    python benchmarks/telemetry_overhead.py --measure  # one arm (internal)
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MIN_TIME = float(os.environ.get("TELEMETRY_BENCH_MIN_TIME", "2.0"))
ROUNDS = int(os.environ.get("TELEMETRY_BENCH_ROUNDS", "2"))


def measure() -> None:
    """One arm: own a fresh cluster, time the sync small-task loop."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def small_value():
            return 0

        # warm the lease + worker so spawn cost stays out of the window
        for _ in range(10):
            ray_tpu.get(small_value.remote())
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < MIN_TIME:
            ray_tpu.get(small_value.remote())
            count += 1
        dt = time.perf_counter() - start
        print(json.dumps({"ops_per_s": round(count / dt, 2),
                          "calls": count}))
    finally:
        ray_tpu.shutdown()


def run_arm(telemetry: str) -> float:
    env = dict(os.environ, RAY_TPU_TELEMETRY=telemetry,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--measure"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return float(json.loads(line)["ops_per_s"])
            except (ValueError, KeyError):
                pass
    raise RuntimeError(
        f"telemetry arm (RAY_TPU_TELEMETRY={telemetry}) produced no "
        f"result: rc={proc.returncode}\n{proc.stderr[-1500:]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure", action="store_true",
                    help="run one measurement arm in-process (internal)")
    args = ap.parse_args()
    if args.measure:
        measure()
        return

    # interleaved rounds: off, on, off, on ... best-of per arm, so a
    # throttle dip in one round can't masquerade as telemetry overhead
    best = {"0": 0.0, "1": 0.0}
    for _ in range(ROUNDS):
        for mode in ("0", "1"):
            best[mode] = max(best[mode], run_arm(mode))
    off, on = best["0"], best["1"]
    overhead_pct = round((off - on) / off * 100.0, 2) if off else 0.0
    rows = [
        {"name": "tasks sync telemetry off", "ops_per_s": off},
        {"name": "tasks sync telemetry on", "ops_per_s": on},
        {"name": "telemetry_overhead", "off_ops_s": off, "on_ops_s": on,
         "overhead_pct": overhead_pct,
         "rounds": ROUNDS, "min_time_s": MIN_TIME},
    ]
    for row in rows:
        print(json.dumps(row))


if __name__ == "__main__":
    main()
