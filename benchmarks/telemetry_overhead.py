"""Telemetry + event + step-stats + tracing + history overhead: A/B bars.

Five always-on observability planes claim record paths cheap enough to
leave on in production, and this bench holds each to a <= 3% bar on its
most instrument-dense path:

* ``python telemetry_overhead.py`` — RAY_TPU_TELEMETRY=0/1 A/B on
  small-task sync throughput (the metrics plane,
  _private/runtime_metrics.py; MICROBENCH ``telemetry`` section).
* ``python telemetry_overhead.py --events`` — RAY_TPU_EVENTS=0/1 A/B
  with telemetry ON in both arms, so the delta isolates the event
  plane (_private/cluster_events.py; MICROBENCH ``events`` section).
* ``python telemetry_overhead.py --step-stats`` — RAY_TPU_STEP_STATS=0/1
  A/B on a fully-clocked ms-scale jax train-step loop (phase contexts,
  per-step metrics, timeline record, GCS report buffering all fire per
  step — the single-chip BENCH workload's instrumentation shape;
  _private/step_stats.py; MICROBENCH ``step_stats`` section).
* ``python telemetry_overhead.py --tracing`` — tracing plane A/B on the
  small-task sync loop at the DEFAULT sample rate (util/tracing/
  tracing_helper.py; MICROBENCH ``tracing`` section).  Paired
  interleaved segments inside ONE subprocess (the --step-stats
  methodology): the plane's cost is ~a random draw per unsampled
  submission plus span recording on the sampled fraction — far below
  what two independent best-of subprocess arms can resolve against
  this box's throttle drift.  The OFF arm flips CONFIG.tracing_enabled
  in the driver, which is the real kill-switch path: with no sampled
  context stamped at submission, the worker side records nothing.
* ``python telemetry_overhead.py --history`` — metrics-history plane
  A/B (_private/metrics_history.py; MICROBENCH ``history`` section).
  The plane's entire cost sits GCS-side on the metrics KV ingest path
  (each flusher write is staged and batch-folded into the retention
  rings at the default 1s/10s/60s geometry), so the paired segments
  drive THAT path: metrics-shaped kv_put RPCs against an in-process
  GcsServer, OFF/ON flipped per segment via
  CONFIG.metrics_history_enabled — the real kill switch (history_on()
  re-resolves on the generation bump, and an in-process server shares
  the driver's CONFIG).  Per-segment statistic = median per-write
  latency; overhead = median of per-pair ratios, same as the
  step-stats/tracing arms.

Arms run in fresh subprocesses, **interleaved** on the same box so the
VM-throttle drift this host suffers hits both arms equally.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MIN_TIME = float(os.environ.get("TELEMETRY_BENCH_MIN_TIME", "2.0"))
ROUNDS = int(os.environ.get("TELEMETRY_BENCH_ROUNDS", "2"))


def measure() -> None:
    """One arm: own a fresh cluster, time the sync small-task loop."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def small_value():
            return 0

        # warm the lease + worker so spawn cost stays out of the window
        for _ in range(10):
            ray_tpu.get(small_value.remote())
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < MIN_TIME:
            ray_tpu.get(small_value.remote())
            count += 1
        dt = time.perf_counter() - start
        print(json.dumps({"ops_per_s": round(count / dt, 2),
                          "calls": count}))
    finally:
        ray_tpu.shutdown()


def measure_steps() -> None:
    """The step-stats A/B, paired: alternating fixed-step-count OFF/ON
    segments in ONE process over a live cluster, overhead = median of
    per-adjacent-pair ratios.

    The plane's cost (~tens of us per step) against a BENCH-shaped
    ms-scale step is ~1%, and this box's throttle drifts by +-20% on a
    minute scale — two independent best-of subprocess arms (the
    telemetry/events methodology) cannot resolve that.  Adjacent
    sub-second segments see the SAME throttle state, so each OFF/ON
    pair yields a clean local ratio; the median over many pairs
    discards the pairs a throttle step landed inside.  OFF segments
    drive the shared no-op clock (exactly what RAY_TPU_STEP_STATS=0
    hands every loop); ON segments drive a live run with the GCS report
    sink and an explicit flush per segment, so shipping cost is charged
    to the ON side instead of leaking into the next OFF segment."""
    import statistics

    import jax
    import jax.numpy as jnp
    import ray_tpu
    from ray_tpu._private import step_stats as sst
    from ray_tpu.runtime import core_worker as cw

    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    try:
        # sized for a BENCH-shaped step (~20-30ms on this box — the
        # single-chip BENCH CPU-smoke step is 35-60ms): the <=3% bar is
        # defined against the BENCH workload's step length, not a
        # microsecond echo loop — the plane's cost is per STEP, so the
        # denominator must be a BENCH-shaped step
        w = jnp.ones((256, 256))
        x = jnp.ones((8192, 256))

        @jax.jit
        def step(w, x):
            g = jax.grad(lambda w: jnp.mean((x @ w) ** 2))(w)
            return w - 0.01 * g

        w = step(w, x)
        jax.block_until_ready(w)
        gcs = cw.get_global_worker().gcs
        run = sst.start_run(
            "overhead-bench", world=1, tokens_per_step=64,
            sink=lambda reports: gcs.call(
                "report_step_stats", {"reports": reports}, timeout=5))
        on_clock = sst.step_clock()

        def segment(clock, nsteps):
            nonlocal w
            t0 = time.perf_counter()
            for _ in range(nsteps):
                clock.begin()
                with clock.phase("host_dispatch"):
                    w = step(w, x)
                with clock.phase("device_compute"):
                    jax.block_until_ready(w)
                clock.end()
            return nsteps / (time.perf_counter() - t0)

        seg_steps = 20
        # ~0.4s per segment: enough pairs that the median shrugs off
        # the multi-second throttle blips a 2-core box throws at a
        # core-saturating matmul loop
        pairs = max(24, int(MIN_TIME * ROUNDS * 6))
        segment(on_clock, seg_steps)       # warm both paths
        segment(sst.NOOP_CLOCK, seg_steps)
        run.flush()
        ratios = []
        off_rates = []
        on_rates = []
        for i in range(pairs):
            # alternate which side of the pair runs first: a monotonic
            # throttle ramp inside a pair would otherwise bias one arm.
            # run.flush() lands BETWEEN timed segments: production ships
            # reports from the flusher thread, never blocking the step
            # loop on the RPC round trip — but the GCS-side processing
            # it triggers bleeds into the NEXT segment, and the
            # alternation distributes that bleed over both arms equally
            if i % 2 == 0:
                off = segment(sst.NOOP_CLOCK, seg_steps)
                on = segment(on_clock, seg_steps)
            else:
                on = segment(on_clock, seg_steps)
                off = segment(sst.NOOP_CLOCK, seg_steps)
            run.flush()
            off_rates.append(off)
            on_rates.append(on)
            ratios.append((off - on) / off)
        sst.end_run(run)
        overhead_pct = round(statistics.median(ratios) * 100.0, 2)
        off_med = round(statistics.median(off_rates), 2)
        on_med = round(statistics.median(on_rates), 2)
        print(json.dumps({"name": "train steps step_stats off",
                          "ops_per_s": off_med}))
        print(json.dumps({"name": "train steps step_stats on",
                          "ops_per_s": on_med}))
        print(json.dumps({"name": "step_stats_overhead",
                          "off_ops_s": off_med, "on_ops_s": on_med,
                          "overhead_pct": overhead_pct,
                          "pairs": pairs, "seg_steps": seg_steps}))
    finally:
        ray_tpu.shutdown()


def measure_tracing() -> None:
    """The tracing A/B, paired: alternating fixed-task-count OFF/ON
    segments in ONE process over a live cluster, per-segment statistic
    = **median per-task latency**, overhead = median of per-pair
    ratios.  ON segments run at the DEFAULT trace_sample_rate — the
    bar is the always-on production configuration, not rate=1.

    Why medians, not throughput: each call here round-trips a worker
    process, and this box throws multi-millisecond scheduler stragglers
    at ~1% of calls (p99 ~900us vs p50 ~310us, max 6-12ms) plus a
    monotonic within-run drift — a segment's ops/s is dominated by
    which segment caught the stragglers, and repeated throughput-based
    runs of this A/B wandered -4%..+10% around a ~1% true cost.  The
    per-task latency MEDIAN is immune to the stragglers by
    construction, and pairing adjacent segments cancels the drift; a
    back-to-back off/on/off distribution check (p50 308.7 / 312.3 /
    314.0 us — the second OFF slower than ON) pins the real p50 cost
    at ~1%."""
    import statistics

    import ray_tpu
    from ray_tpu._private.config import CONFIG
    from ray_tpu.util.tracing import tracing_helper as trh

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def small_value():
            return 0

        for _ in range(10):   # warm the lease + worker
            ray_tpu.get(small_value.remote())

        def segment(ntasks) -> float:
            """Median per-task latency (us) over one segment."""
            lats = []
            for _ in range(ntasks):
                t0 = time.perf_counter()
                ray_tpu.get(small_value.remote())
                lats.append(time.perf_counter() - t0)
            return statistics.median(lats) * 1e6

        def arm(on: bool, ntasks: int) -> float:
            # CONFIG.set bumps the generation, so the cached sampler
            # flags re-resolve immediately — the driver stops stamping
            # trace contexts, and with no sampled context in the spec
            # the worker side records nothing either
            CONFIG.set("tracing_enabled", on)
            try:
                return segment(ntasks)
            finally:
                CONFIG.set("tracing_enabled", True)

        seg_tasks = 200
        pairs = max(32, int(MIN_TIME * ROUNDS * 8))
        arm(True, seg_tasks)    # warm both paths
        arm(False, seg_tasks)
        ratios, off_lats, on_lats = [], [], []
        for i in range(pairs):
            if i % 2 == 0:
                off = arm(False, seg_tasks)
                on = arm(True, seg_tasks)
            else:
                on = arm(True, seg_tasks)
                off = arm(False, seg_tasks)
            # ship buffered spans between timed segments (production
            # ships from the flusher thread; the GCS-side processing
            # bleed lands on both arms via the alternation)
            trh.flush_now()
            off_lats.append(off)
            on_lats.append(on)
            ratios.append((on - off) / off)
        overhead_pct = round(statistics.median(ratios) * 100.0, 2)
        off_med = round(statistics.median(off_lats), 2)
        on_med = round(statistics.median(on_lats), 2)
        print(json.dumps({"name": "tasks sync tracing off",
                          "p50_us": off_med,
                          "ops_per_s": round(1e6 / off_med, 2)}))
        print(json.dumps({"name": "tasks sync tracing on",
                          "p50_us": on_med,
                          "ops_per_s": round(1e6 / on_med, 2)}))
        print(json.dumps({"name": "tracing_overhead",
                          "off_p50_us": off_med, "on_p50_us": on_med,
                          "overhead_pct": overhead_pct,
                          "sample_rate": CONFIG.trace_sample_rate,
                          "pairs": pairs, "seg_tasks": seg_tasks}))
    finally:
        ray_tpu.shutdown()


def measure_history() -> None:
    """The metrics-history A/B, paired: alternating fixed-write-count
    OFF/ON segments of metrics-shaped kv_put RPCs against an
    in-process GcsServer, per-segment statistic = median per-write
    latency, overhead = median of per-pair ratios.

    The history plane's only hot path is GCS-side: every metrics KV
    write additionally stages for the per-series retention rings and
    every 64th write folds the batch (_private/metrics_history.py), so
    the denominator must be the real ingest path — an RPC round trip
    into _rpc_kv_put — not a bare in-process record() call, which
    would compare ring arithmetic against nothing.  The GCS normally
    runs as a subprocess daemon, so
    a driver-side CONFIG.set could never reach it; an in-process
    GcsServer shares this process's CONFIG, which lets the paired arms
    flip CONFIG.metrics_history_enabled per segment — the production
    kill switch, re-resolved by history_on() on the generation bump.
    Writes rotate over a worker-sized fan of distinct series so ring
    appends, live-bucket overwrites, and the byte-budget sweep all get
    exercised at the default 1s/10s/60s geometry."""
    import statistics

    from ray_tpu._private import rpc
    from ray_tpu._private.config import CONFIG
    from ray_tpu.runtime.gcs import GcsServer

    gcs = GcsServer()
    conn = rpc.connect(gcs.address)
    try:
        # one flusher write: the runtime-metrics wire shape (a handful
        # of tagged values per series, as a real worker flush carries)
        payload = json.dumps({
            "type": "histogram", "description": "history bench series",
            "values": {json.dumps({"method": f"m{i}"}):
                       {"count": 10 + i, "sum": 1.5 * i,
                        "buckets": [0, 1, 2, 3, 4, 0, 0, 0]}
                       for i in range(4)},
            "ts": time.time(), "runtime": True}).encode()
        nseries = 32   # worker-sized flush fan of distinct series
        seq = [0]

        def segment(nwrites: int) -> float:
            """Median per-write latency (us) over one segment."""
            lats = []
            for _ in range(nwrites):
                i = seq[0] = seq[0] + 1
                key = f"metrics/ray_tpu_bench_hist_{i % nseries}/proc"
                t0 = time.perf_counter()
                conn.call("kv_put", {"key": key, "value": payload})
                lats.append(time.perf_counter() - t0)
            return statistics.median(lats) * 1e6

        def arm(on: bool, nwrites: int) -> float:
            # the in-process server's ingest thread shares this CONFIG:
            # the set() bumps the generation and history_on() in
            # _rpc_kv_put re-resolves — the same path RAY_TPU doctor
            # users take to kill the plane on a live config push
            CONFIG.set("metrics_history_enabled", on)
            try:
                return segment(nwrites)
            finally:
                CONFIG.set("metrics_history_enabled", True)

        seg_writes = 200
        pairs = max(32, int(MIN_TIME * ROUNDS * 8))
        arm(True, seg_writes)    # warm both paths (rings populated)
        arm(False, seg_writes)
        ratios, off_lats, on_lats = [], [], []
        for i in range(pairs):
            if i % 2 == 0:
                off = arm(False, seg_writes)
                on = arm(True, seg_writes)
            else:
                on = arm(True, seg_writes)
                off = arm(False, seg_writes)
            off_lats.append(off)
            on_lats.append(on)
            ratios.append((on - off) / off)
        overhead_pct = round(statistics.median(ratios) * 100.0, 2)
        off_med = round(statistics.median(off_lats), 2)
        on_med = round(statistics.median(on_lats), 2)
        hist_stats = conn.call("metrics_history_stats", {})
        print(json.dumps({"name": "metrics ingest history off",
                          "p50_us": off_med,
                          "ops_per_s": round(1e6 / off_med, 2)}))
        print(json.dumps({"name": "metrics ingest history on",
                          "p50_us": on_med,
                          "ops_per_s": round(1e6 / on_med, 2)}))
        print(json.dumps({"name": "history_overhead",
                          "off_p50_us": off_med, "on_p50_us": on_med,
                          "overhead_pct": overhead_pct,
                          "pairs": pairs, "seg_writes": seg_writes,
                          "series": hist_stats.get("series"),
                          "points": hist_stats.get("points"),
                          "bytes": hist_stats.get("bytes")}))
    finally:
        conn.close()
        gcs.stop()


def _run_measure(measure_flag: str, env_overrides: dict) -> list:
    """One measurement subprocess -> its parsed JSON stdout rows."""
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               **env_overrides)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), measure_flag],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    if not rows:
        raise RuntimeError(
            f"arm {measure_flag} {env_overrides} produced no result: "
            f"rc={proc.returncode}\n{proc.stderr[-1500:]}")
    return rows


def run_arm(env_overrides: dict, measure_flag: str = "--measure") -> float:
    for row in _run_measure(measure_flag, env_overrides):
        if "ops_per_s" in row:
            return float(row["ops_per_s"])
    raise RuntimeError(f"arm {env_overrides}: no ops_per_s row")


def ab(kill_var: str, base_env: dict, label: str,
       measure_flag: str = "--measure",
       workload: str = "tasks sync") -> list:
    """Interleaved rounds, best-of per arm, so a throttle dip in one
    round can't masquerade as plane overhead.  The within-round order
    ALTERNATES (0,1 then 1,0): whichever arm runs first in a round
    pays the previous subprocess's teardown tail (dying workers, store
    cleanup), and a fixed order turns that into a systematic bias."""
    best = {"0": 0.0, "1": 0.0}
    for i in range(ROUNDS):
        order = ("0", "1") if i % 2 == 0 else ("1", "0")
        for mode in order:
            best[mode] = max(best[mode],
                             run_arm(dict(base_env, **{kill_var: mode}),
                                     measure_flag))
    off, on = best["0"], best["1"]
    overhead_pct = round((off - on) / off * 100.0, 2) if off else 0.0
    return [
        {"name": f"{workload} {label} off", "ops_per_s": off},
        {"name": f"{workload} {label} on", "ops_per_s": on},
        {"name": f"{label}_overhead", "off_ops_s": off, "on_ops_s": on,
         "overhead_pct": overhead_pct,
         "rounds": ROUNDS, "min_time_s": MIN_TIME},
    ]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure", action="store_true",
                    help="run one measurement arm in-process (internal)")
    ap.add_argument("--measure-steps", action="store_true",
                    help="run one step-stats measurement arm (internal)")
    ap.add_argument("--measure-tracing", action="store_true",
                    help="run one tracing measurement arm (internal)")
    ap.add_argument("--measure-history", action="store_true",
                    help="run one metrics-history measurement arm "
                         "(internal)")
    ap.add_argument("--tracing", action="store_true",
                    help="A/B the request tracing plane "
                         "(CONFIG.tracing_enabled) on the small-task "
                         "loop at the default sample rate")
    ap.add_argument("--history", action="store_true",
                    help="A/B the metrics-history plane "
                         "(CONFIG.metrics_history_enabled) on the GCS "
                         "metrics ingest path at the default retention")
    ap.add_argument("--events", action="store_true",
                    help="A/B the event plane (RAY_TPU_EVENTS) instead "
                         "of the metrics plane")
    ap.add_argument("--step-stats", dest="step_stats",
                    action="store_true",
                    help="A/B the training performance plane "
                         "(RAY_TPU_STEP_STATS) on a clocked step loop")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override TELEMETRY_BENCH_ROUNDS (best-of "
                         "interleaved rounds; more rounds = more "
                         "resistance to this box's throttle drift)")
    ap.add_argument("--min-time", type=float, default=None,
                    help="override TELEMETRY_BENCH_MIN_TIME (seconds "
                         "per arm per round)")
    args = ap.parse_args()
    global ROUNDS, MIN_TIME
    if args.rounds is not None:
        ROUNDS = args.rounds
    if args.min_time is not None:
        MIN_TIME = args.min_time
        os.environ["TELEMETRY_BENCH_MIN_TIME"] = str(args.min_time)
    if args.measure:
        measure()
        return
    if args.measure_steps:
        measure_steps()
        return
    if args.measure_tracing:
        measure_tracing()
        return
    if args.measure_history:
        measure_history()
        return
    if args.tracing:
        # one subprocess, paired interleaved OFF/ON segments (see
        # measure_tracing); telemetry+events pinned on in both arms so
        # the delta isolates the tracing plane
        # NOTE: no RAY_TPU_TRACING in the env — the env override beats
        # CONFIG.tracing_enabled, and the paired arms flip the CONFIG
        # flag; an env pin would force both arms ON
        rows = _run_measure("--measure-tracing", {
            "RAY_TPU_TELEMETRY": "1", "RAY_TPU_EVENTS": "1",
            "TELEMETRY_BENCH_ROUNDS": str(ROUNDS),
            "TELEMETRY_BENCH_MIN_TIME": str(MIN_TIME)})
        for row in rows:
            print(json.dumps(row))
        return
    if args.history:
        # one subprocess, paired interleaved OFF/ON segments against an
        # in-process GcsServer (see measure_history)
        # NOTE: no RAY_TPU_METRICS_HISTORY in the env — the env
        # override beats CONFIG.metrics_history_enabled, and the paired
        # arms flip the CONFIG flag; an env pin would force both arms
        rows = _run_measure("--measure-history", {
            "RAY_TPU_TELEMETRY": "1", "RAY_TPU_EVENTS": "1",
            "TELEMETRY_BENCH_ROUNDS": str(ROUNDS),
            "TELEMETRY_BENCH_MIN_TIME": str(MIN_TIME)})
        for row in rows:
            print(json.dumps(row))
        return
    if args.step_stats:
        # one subprocess, paired interleaved OFF/ON segments inside it
        # (see measure_steps): the OFF arm IS the no-op clock the kill
        # switch hands out, and paired segments beat throttle drift
        rows = _run_measure("--measure-steps", {
            "RAY_TPU_TELEMETRY": "1", "RAY_TPU_EVENTS": "1",
            "RAY_TPU_STEP_STATS": "1",
            "TELEMETRY_BENCH_ROUNDS": str(ROUNDS),
            "TELEMETRY_BENCH_MIN_TIME": str(MIN_TIME)})
    elif args.events:
        # telemetry pinned ON in both arms: the delta is the event plane
        rows = ab("RAY_TPU_EVENTS", {"RAY_TPU_TELEMETRY": "1"}, "events")
    else:
        rows = ab("RAY_TPU_TELEMETRY", {}, "telemetry")
    for row in rows:
        print(json.dumps(row))


if __name__ == "__main__":
    main()
