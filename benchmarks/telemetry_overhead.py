"""Telemetry + event-plane overhead guards: task throughput A/B.

Two always-on observability planes claim record paths cheap enough to
leave on in production, and this bench holds each to a <= 3% bar on the
single most instrument-dense path (small-task sync throughput — RPC
dispatch, submit, push batch, e2e latency, execution timing, and the
per-task flight-recorder breadcrumb all fire per task):

* ``python telemetry_overhead.py`` — RAY_TPU_TELEMETRY=0/1 A/B
  (the metrics plane, _private/runtime_metrics.py; MICROBENCH
  ``telemetry`` section).
* ``python telemetry_overhead.py --events`` — RAY_TPU_EVENTS=0/1 A/B
  with telemetry ON in both arms, so the delta isolates the event
  plane (_private/cluster_events.py; MICROBENCH ``events`` section).

Arms run in fresh subprocesses, **interleaved** on the same box so the
VM-throttle drift this host suffers hits both arms equally.
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MIN_TIME = float(os.environ.get("TELEMETRY_BENCH_MIN_TIME", "2.0"))
ROUNDS = int(os.environ.get("TELEMETRY_BENCH_ROUNDS", "2"))


def measure() -> None:
    """One arm: own a fresh cluster, time the sync small-task loop."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def small_value():
            return 0

        # warm the lease + worker so spawn cost stays out of the window
        for _ in range(10):
            ray_tpu.get(small_value.remote())
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < MIN_TIME:
            ray_tpu.get(small_value.remote())
            count += 1
        dt = time.perf_counter() - start
        print(json.dumps({"ops_per_s": round(count / dt, 2),
                          "calls": count}))
    finally:
        ray_tpu.shutdown()


def run_arm(env_overrides: dict) -> float:
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
               **env_overrides)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--measure"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return float(json.loads(line)["ops_per_s"])
            except (ValueError, KeyError):
                pass
    raise RuntimeError(
        f"arm {env_overrides} produced no result: "
        f"rc={proc.returncode}\n{proc.stderr[-1500:]}")


def ab(kill_var: str, base_env: dict, label: str) -> list:
    """Interleaved rounds, best-of per arm, so a throttle dip in one
    round can't masquerade as plane overhead.  The within-round order
    ALTERNATES (0,1 then 1,0): whichever arm runs first in a round
    pays the previous subprocess's teardown tail (dying workers, store
    cleanup), and a fixed order turns that into a systematic bias."""
    best = {"0": 0.0, "1": 0.0}
    for i in range(ROUNDS):
        order = ("0", "1") if i % 2 == 0 else ("1", "0")
        for mode in order:
            best[mode] = max(best[mode],
                             run_arm(dict(base_env, **{kill_var: mode})))
    off, on = best["0"], best["1"]
    overhead_pct = round((off - on) / off * 100.0, 2) if off else 0.0
    return [
        {"name": f"tasks sync {label} off", "ops_per_s": off},
        {"name": f"tasks sync {label} on", "ops_per_s": on},
        {"name": f"{label}_overhead", "off_ops_s": off, "on_ops_s": on,
         "overhead_pct": overhead_pct,
         "rounds": ROUNDS, "min_time_s": MIN_TIME},
    ]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure", action="store_true",
                    help="run one measurement arm in-process (internal)")
    ap.add_argument("--events", action="store_true",
                    help="A/B the event plane (RAY_TPU_EVENTS) instead "
                         "of the metrics plane")
    args = ap.parse_args()
    if args.measure:
        measure()
        return
    if args.events:
        # telemetry pinned ON in both arms: the delta is the event plane
        rows = ab("RAY_TPU_EVENTS", {"RAY_TPU_TELEMETRY": "1"}, "events")
    else:
        rows = ab("RAY_TPU_TELEMETRY", {}, "telemetry")
    for row in rows:
        print(json.dumps(row))


if __name__ == "__main__":
    main()
