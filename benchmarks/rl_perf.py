"""RL north-star benchmarks: PPO CartPole to reward 150 + IMPALA throughput.

Counterpart of the reference's tuned examples:
  - rllib/tuned_examples/ppo/cartpole-ppo.yaml (episode_reward_mean >= 150
    within 100k env steps) — the PPO north-star row.
  - rllib/tuned_examples/impala/pong-impala-fast.yaml (reward 18-19 in
    ~3 min on a p3.16xl + 3x m4.16xl) — the IMPALA north-star row.  That
    bar is a 100+-core GPU-cluster learning-speed target; this box has ONE
    physical core, so the honest scaled analog reported here is the async
    actor-learner pipeline's throughput: IMPALA env-steps/s on CartPole
    (with the learning check) and on a synthetic fixed-episode wide-
    observation env (pure pipeline load, no learnable signal — labeled
    synthetic).

  python benchmarks/rl_perf.py [--target 150] [--max-steps 100000]

Prints one JSON line per row.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run(target=150.0, max_steps=100_000, seed=0):
    import ray_tpu
    from ray_tpu.rl import PPOConfig

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=125)
            .training(train_batch_size=1000, sgd_minibatch_size=250,
                      num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
                      gamma=0.99)
            .debugging(seed=seed)
            .build())
    t0 = time.monotonic()
    reached_at_s = None
    reached_at_steps = None
    iters = 0
    best = float("-inf")
    sgd_steps = 0
    try:
        while True:
            result = algo.train()
            iters += 1
            reward = result["episode_reward_mean"]
            best = max(best, reward)
            steps = result["timesteps_total"]
            sgd_steps = result.get("num_sgd_steps_total", 0) or \
                iters * 8 * (1000 // 250)
            if reward >= target and reached_at_s is None:
                reached_at_s = time.monotonic() - t0
                reached_at_steps = steps
                break
            if steps >= max_steps:
                break
            print(f"  [rl] iter {iters}: reward {reward:.1f} "
                  f"steps {steps}", flush=True)
        wall = time.monotonic() - t0
        return {
            "metric": "rl_ppo_cartpole",
            "target_reward": target,
            "reached": reached_at_s is not None,
            "time_to_target_s": (round(reached_at_s, 1)
                                 if reached_at_s else None),
            "env_steps_to_target": reached_at_steps,
            "best_reward": round(best, 1),
            "train_iters": iters,
            "env_steps_total": steps,
            "env_steps_per_s": round(steps / wall, 1),
            "sgd_steps_per_s": round(sgd_steps / wall, 1),
            "reference": "rllib tuned cartpole-ppo.yaml: reward 150 "
                         "within 100k env steps",
        }
    finally:
        algo.stop()
        ray_tpu.shutdown()


class SyntheticWideEnv:
    """Fixed-200-step episodes over a 512-dim observation: measures the
    async pipeline (rollout actors -> learner queue -> V-trace sgd ->
    weight push) under a Pong-preprocessed-scale observation payload
    without pretending a 1-core box can learn Pong."""

    def __init__(self):
        from ray_tpu.rl.env import Box, Discrete
        self.observation_space = Box(-1.0, 1.0, (512,))
        self.action_space = Discrete(6)      # Atari Pong action count
        self._rng = __import__("numpy").random.default_rng(0)
        self._t = 0

    def reset(self, *, seed=None):
        import numpy as np
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._rng.standard_normal(512).astype(np.float32), {}

    def step(self, action):
        import numpy as np
        self._t += 1
        obs = self._rng.standard_normal(512).astype(np.float32)
        return obs, float(action == 0), False, self._t >= 200, {}

    def close(self):
        pass


def run_impala(env_spec, label, target, max_steps, train_iters, seed=0):
    import ray_tpu
    from ray_tpu.rl import ImpalaConfig

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    algo = (ImpalaConfig()
            .environment(env_spec)
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=50)
            .training(lr=5e-4, entropy_coeff=0.01, gamma=0.99)
            .debugging(seed=seed)
            .build())
    t0 = time.monotonic()
    best = float("-inf")
    reached_at_s = None
    reached_at_steps = None
    steps = 0
    iters = 0
    try:
        while True:
            result = algo.train()
            iters += 1
            reward = result["episode_reward_mean"]
            best = max(best, reward)
            steps = result["timesteps_total"]
            if (target is not None and reward >= target
                    and reached_at_s is None):
                reached_at_s = time.monotonic() - t0
                reached_at_steps = steps
                break
            if steps >= max_steps or iters >= train_iters:
                break
        wall = time.monotonic() - t0
        row = {
            "metric": f"rl_impala_{label}",
            "env_steps_total": steps,
            "env_steps_per_s": round(steps / wall, 1),
            "train_iters": iters,
            "best_reward": round(best, 1),
            "wall_s": round(wall, 1),
            "reference": "rllib pong-impala-fast.yaml: reward 18-19 in "
                         "~3 min on p3.16xl + 3x m4.16xl (100+ cores); "
                         "this box: 1 physical core — throughput analog",
        }
        if target is not None:
            row.update({
                "target_reward": target,
                "reached": reached_at_s is not None,
                "time_to_target_s": (round(reached_at_s, 1)
                                     if reached_at_s else None),
                "env_steps_to_target": reached_at_steps,
            })
        return row
    finally:
        algo.stop()
        ray_tpu.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=float, default=150.0)
    ap.add_argument("--max-steps", type=int, default=100_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--only", choices=["ppo", "impala"], default=None)
    args = ap.parse_args()
    if args.only in (None, "ppo"):
        print(json.dumps(run(args.target, args.max_steps, args.seed)))
        sys.stdout.flush()
    if args.only in (None, "impala"):
        # learning row: CartPole to the PPO bar (IMPALA is noisier off-
        # policy; cap the budget) + synthetic pipeline-throughput row
        print(json.dumps(run_impala("CartPole-v1", "cartpole",
                                    args.target, args.max_steps,
                                    train_iters=10_000, seed=args.seed)))
        sys.stdout.flush()
        print(json.dumps(run_impala(SyntheticWideEnv, "synthetic_wide",
                                    None, 120_000, train_iters=10_000,
                                    seed=args.seed)))


if __name__ == "__main__":
    main()
