"""RL north-star benchmark: PPO CartPole to reward 150.

Counterpart of the reference's tuned example
(rllib/tuned_examples/ppo/cartpole-ppo.yaml: episode_reward_mean >= 150
within 100k env steps) — the second BASELINE.md north-star row.  Reports
wall time to the target, env steps consumed, and learner throughput.

  python benchmarks/rl_perf.py [--target 150] [--max-steps 100000]

Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def run(target=150.0, max_steps=100_000, seed=0):
    import ray_tpu
    from ray_tpu.rl import PPOConfig

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=125)
            .training(train_batch_size=1000, sgd_minibatch_size=250,
                      num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
                      gamma=0.99)
            .debugging(seed=seed)
            .build())
    t0 = time.monotonic()
    reached_at_s = None
    reached_at_steps = None
    iters = 0
    best = float("-inf")
    sgd_steps = 0
    try:
        while True:
            result = algo.train()
            iters += 1
            reward = result["episode_reward_mean"]
            best = max(best, reward)
            steps = result["timesteps_total"]
            sgd_steps = result.get("num_sgd_steps_total", 0) or \
                iters * 8 * (1000 // 250)
            if reward >= target and reached_at_s is None:
                reached_at_s = time.monotonic() - t0
                reached_at_steps = steps
                break
            if steps >= max_steps:
                break
            print(f"  [rl] iter {iters}: reward {reward:.1f} "
                  f"steps {steps}", flush=True)
        wall = time.monotonic() - t0
        return {
            "metric": "rl_ppo_cartpole",
            "target_reward": target,
            "reached": reached_at_s is not None,
            "time_to_target_s": (round(reached_at_s, 1)
                                 if reached_at_s else None),
            "env_steps_to_target": reached_at_steps,
            "best_reward": round(best, 1),
            "train_iters": iters,
            "env_steps_total": steps,
            "env_steps_per_s": round(steps / wall, 1),
            "sgd_steps_per_s": round(sgd_steps / wall, 1),
            "reference": "rllib tuned cartpole-ppo.yaml: reward 150 "
                         "within 100k env steps",
        }
    finally:
        algo.stop()
        ray_tpu.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=float, default=150.0)
    ap.add_argument("--max-steps", type=int, default=100_000)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    print(json.dumps(run(args.target, args.max_steps, args.seed)))


if __name__ == "__main__":
    main()
