"""Object-transfer data-plane microbenchmark (docs/object_transfer.md).

Measures the three capabilities the data plane v2 exists for, each as an
**interleaved same-box A/B** so this box's VM-throttle drift hits both
arms equally (medians of per-round rates are reported):

* **pipelined vs serial pull** — one 64 MiB object pulled from one
  source through the data plane (window 8, zero-copy chunk serving,
  buffer-sink recv_into shm) vs the pre-v2 serial algorithm, reproduced
  verbatim in ``legacy_serial_pull`` below: throwaway TCP connection,
  one blocking ``bytes()``-copied chunk per RTT, bytearray assembly and
  a final whole-object copy.  The >=3x speedup bar.
* **striped vs single-source pull** — the same object pulled with its
  chunk ranges striped across two nodes holding live copies vs one,
  both arms under a deterministic background CPU load emulating busy
  source hosts (the production regime: a TPU host serving weights is
  mid-training, and its scheduling stalls bubble a single source's
  pipeline — the stalls striping exists to fill.  On an idle loopback
  box client and server per-byte costs are symmetric, so a second
  source has no spare core to add and the ratio reads ~1.0 regardless
  of the striping implementation).  The >1x bar.
* **prefetch-overlap task e2e** — a task whose fresh 256 MiB argument
  lives only on the head node, pinned to a worker node with a unique
  runtime_env (cold worker spawn every round): with argument prefetch
  the transfer overlaps the spawn, without it they serialize.  The
  saved_ms row is the overlap.

Also asserts the zero-copy contract: one pull grows the client store by
exactly one object (bytes_delta == object size, not the 2-3x of the old
bytearray + bytes() + store-put assembly).

Prints JSON lines (names are collect_microbench delta keys):
  {"name": "pull 64MiB serial",          "mb_per_s": ...}
  {"name": "pull 64MiB pipelined",       "mb_per_s": ...}
  {"name": "pipelined vs serial pull bandwidth", "speedup": ...}  # >=3x
  {"name": "pull 64MiB 1-source busy hosts", "mb_per_s": ...}
  {"name": "pull 64MiB striped 2-source busy hosts", "mb_per_s": ...}
  {"name": "striped 2-source vs 1-source", "speedup": ...}        # >1x
  {"name": "pull shm growth", "bytes_delta", "object_bytes"}      # ==
  {"name": "task e2e 256MiB arg prefetch off", "e2e_ms": ...}
  {"name": "task e2e 256MiB arg prefetch on",  "e2e_ms": ...}
  {"name": "prefetch overlap saving", "saved_ms": ...}
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# 1 MiB chunks: 64 chunks per object, so the serial arm pays 64 blocking
# RTTs and the window/striping arms have real ranges to overlap/split
os.environ["RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES"] = str(1024 * 1024)

ROUNDS = int(os.environ.get("OBJECT_TRANSFER_BENCH_ROUNDS", "5"))
E2E_ROUNDS = int(os.environ.get("OBJECT_TRANSFER_BENCH_E2E_ROUNDS", "5"))
OBJ_BYTES = 64 * 1024 * 1024
ELEMS = OBJ_BYTES // 8
# e2e arm: a bigger argument so the transfer is resolvable against the
# cold worker spawn's natural variance
E2E_ELEMS = 4 * ELEMS


def _pull_once(w, oid, sources, window):
    """One cold pull into the local store; returns (seconds, bytes_delta).
    Leaves the store as it found it."""
    from ray_tpu._private.config import CONFIG
    CONFIG.set("object_pull_window", window)
    before = w.store.stats()["bytes_in_use"]
    t0 = time.perf_counter()
    out = w._puller.pull(oid, sources)
    dt = time.perf_counter() - t0
    assert out.status == "ok" and out.published, \
        f"pull failed: {out.status} absent={out.absent}"
    delta = w.store.stats()["bytes_in_use"] - before
    out.data.release()
    w.store.release(oid)
    assert w.store.delete(oid), "cleanup delete failed"
    return dt, delta


def legacy_serial_pull(w, oid, node_hex):
    """The pre-v2 ``_fetch_remote`` algorithm, verbatim: a throwaway TCP
    connection, one blocking chunk per RTT with the server's in-band
    ``bytes()`` copy-out, client-side bytearray assembly and a final
    whole-object ``bytes(out)`` copy.  Returns seconds."""
    from ray_tpu._private import rpc
    from ray_tpu._private.config import CONFIG
    chunk = CONFIG.object_transfer_chunk_bytes
    addr = w._node_address(node_hex)
    t0 = time.perf_counter()
    conn = rpc.connect(addr, timeout=5.0)
    try:
        first = conn.call("fetch_object_chunk",
                          {"object_id": oid.binary(), "offset": 0,
                           "length": chunk, "timeout": 0.0},
                          timeout=60)
        total = first["total"]
        out = bytearray(total)
        out[:len(first["data"])] = first["data"]
        off = len(first["data"])
        while off < total:
            res = conn.call("fetch_object_chunk",
                            {"object_id": oid.binary(), "offset": off,
                             "length": chunk, "timeout": 0.0},
                            timeout=60)
            out[off:off + len(res["data"])] = res["data"]
            off += len(res["data"])
        data = bytes(out)
    finally:
        conn.close()
    dt = time.perf_counter() - t0
    assert len(data) == total and total >= OBJ_BYTES
    return dt


def _spin(stop_name):
    """Busy-loop until the stop file appears (background host load)."""
    import os as _os
    x = 0
    while not _os.path.exists(stop_name):
        for _ in range(10000):
            x = (x * 1103515245 + 12345) & 0x7FFFFFFF
    return x


class _BusyHosts:
    """Deterministic background CPU load (one spinner per core) around
    the striped A/B: both arms see identical contention, the only
    variable is the source count."""

    def __init__(self, n=2):
        import multiprocessing as mp
        import tempfile
        fd, self._stop = tempfile.mkstemp(prefix="bench_spin_stop_")
        os.close(fd)
        os.unlink(self._stop)
        self._procs = [mp.Process(target=_spin, args=(self._stop,),
                                  daemon=True) for _ in range(n)]

    def __enter__(self):
        for p in self._procs:
            p.start()
        time.sleep(0.2)  # let the spinners reach steady state
        return self

    def __exit__(self, *exc):
        with open(self._stop, "w"):
            pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        os.unlink(self._stop)


def bandwidth_arms(ray_tpu, cluster, src1, src2):
    from ray_tpu.runtime.core_worker import get_global_worker

    @ray_tpu.remote(resources={"src1": 1}, num_cpus=1)
    def produce():
        import numpy as np
        return np.arange(ELEMS, dtype=np.float64)

    @ray_tpu.remote(resources={"src2": 1}, num_cpus=1)
    def replicate(x):
        return float(x[-1])

    ref = produce.remote()
    assert ray_tpu.get(replicate.remote(ref),
                       timeout=300) == float(ELEMS - 1)
    w = get_global_worker()
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        with w._owned_lock:
            locs = set(w._owned[ref.id].locations)
        if {src1.node_id, src2.node_id} <= locs:
            break
        time.sleep(0.1)
    assert {src1.node_id, src2.node_id} <= locs, f"not replicated: {locs}"

    # zero-copy contract: one pull = one object's worth of store growth
    _pull_once(w, ref.id, [src1.node_id], 8)  # warm conns + store
    _t, delta = _pull_once(w, ref.id, [src1.node_id], 8)
    print(json.dumps({"name": "pull shm growth",
                      "bytes_delta": int(delta),
                      "object_bytes": OBJ_BYTES}), flush=True)

    serial_s, piped_s = [], []
    for _round in range(ROUNDS):
        serial_s.append(legacy_serial_pull(w, ref.id, src1.node_id))
        piped_s.append(_pull_once(w, ref.id, [src1.node_id], 8)[0])

    # striped A/B under busy source hosts (see module docstring): the
    # 1-source baseline re-measures under the same load — comparing
    # striped-under-load to the idle number above would be meaningless
    # more rounds here than the idle arms: under contention each round's
    # rate is noisier, and the A/B margin is smaller — a tighter median
    # costs only ~0.5 s per extra round pair
    one_busy_s, striped_s = [], []
    with _BusyHosts():
        for _round in range(max(ROUNDS, 9)):
            one_busy_s.append(_pull_once(w, ref.id, [src1.node_id], 8)[0])
            striped_s.append(_pull_once(
                w, ref.id, [src1.node_id, src2.node_id], 8)[0])

    mb = OBJ_BYTES / 1024 / 1024
    ser = mb / statistics.median(serial_s)
    pip = mb / statistics.median(piped_s)
    one = mb / statistics.median(one_busy_s)
    stp = mb / statistics.median(striped_s)
    print(json.dumps({"name": "pull 64MiB serial",
                      "mb_per_s": round(ser, 1)}), flush=True)
    print(json.dumps({"name": "pull 64MiB pipelined",
                      "mb_per_s": round(pip, 1)}), flush=True)
    print(json.dumps({"name": "pipelined vs serial pull bandwidth",
                      "speedup": round(pip / ser, 2)}), flush=True)
    print(json.dumps({"name": "pull 64MiB 1-source busy hosts",
                      "mb_per_s": round(one, 1)}), flush=True)
    print(json.dumps({"name": "pull 64MiB striped 2-source busy hosts",
                      "mb_per_s": round(stp, 1)}), flush=True)
    print(json.dumps({"name": "striped 2-source vs 1-source",
                      "speedup": round(stp / one, 2)}), flush=True)
    del ref


def e2e_arms(ray_tpu, dst):
    """Cold-worker task e2e with a fresh 256 MiB argument, prefetch
    on/off interleaved.  A unique runtime_env per round forces a worker
    spawn, the window prefetch exists to overlap with."""
    import numpy as np

    from ray_tpu._private.config import CONFIG
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    pin_dst = NodeAffinitySchedulingStrategy(dst.node_id)

    @ray_tpu.remote(num_cpus=1, scheduling_strategy=pin_dst)
    def consume(x):
        return float(x[-1])

    def one(tag, prefetch_on):
        CONFIG.set("object_prefetch_enabled", prefetch_on)
        CONFIG.set("locality_aware_scheduling", prefetch_on)
        big = ray_tpu.put(np.arange(E2E_ELEMS, dtype=np.float64))
        t0 = time.perf_counter()
        ref = consume.options(
            runtime_env={"env_vars": {"BENCH_COLD": tag}}).remote(big)
        assert ray_tpu.get(ref, timeout=300) == float(E2E_ELEMS - 1)
        dt = time.perf_counter() - t0
        del ref, big
        time.sleep(0.5)  # let the frees sweep the copies off dst
        return dt * 1e3

    one("warm", True)  # pay one-time costs (function export etc.)
    on_ms, off_ms = [], []
    for r in range(E2E_ROUNDS):
        off_ms.append(one(f"off{r}", False))
        on_ms.append(one(f"on{r}", True))
    CONFIG.set("object_prefetch_enabled", True)
    CONFIG.set("locality_aware_scheduling", True)

    off = statistics.median(off_ms)
    on = statistics.median(on_ms)
    # the overlap is the median of per-round pair differences: adjacent
    # off/on runs see similar box throttle, so slow drift cancels
    saved = statistics.median(o - n for o, n in zip(off_ms, on_ms))
    print(json.dumps({"name": "task e2e 256MiB arg prefetch off",
                      "e2e_ms": round(off, 1)}), flush=True)
    print(json.dumps({"name": "task e2e 256MiB arg prefetch on",
                      "e2e_ms": round(on, 1)}), flush=True)
    print(json.dumps({"name": "prefetch overlap saving",
                      "saved_ms": round(saved, 1)}), flush=True)


def main():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(object_store_memory=512 * 1024 * 1024)
    try:
        src1 = cluster.add_node(resources={"CPU": 2, "src1": 2})
        src2 = cluster.add_node(resources={"CPU": 2, "src2": 2})
        cluster.wait_for_nodes(3)
        ray_tpu.init(num_cpus=1, address=cluster.address)
        bandwidth_arms(ray_tpu, cluster, src1, src2)
        # the e2e consumer node joins only now: its raylet + worker pool
        # must not steal cycles from the bandwidth arms on a small box
        dst = cluster.add_node(resources={"CPU": 4, "dst": 8})
        cluster.wait_for_nodes(4)
        e2e_arms(ray_tpu, dst)
        ray_tpu.shutdown()
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
