"""Serving front-door closed-loop harness (docs/serve_frontdoor.md).

The MICROBENCH `serve_frontdoor` section: a bimodal shared-prefix mix
sustained at constant concurrency against the full front-door stack —
SSE streaming ingress through the HTTP proxy, prefix-affinity routing
into a prefix-caching prefill pool, int8-quantized KV handoffs — with
the SLO plane doing the verdicts: every stream closes an ingress trace
root, and the row reports the per-pool (route) TTFT/TPOT good/violation
classification straight from ``trace_stats()``.

Connection split: real OS sockets cap the pure-HTTP arm (each SSE
stream holds a client fd AND a server fd against a 20k box limit), so
``http_conns`` of the ``connections`` logical clients stream over real
HTTP/SSE through the proxy and the rest drive the same DisaggHandle
router in-process (identical routing, prefix-affinity, retry and SLO
accounting paths — the HTTP arm adds only the aiohttp transport).  The
row carries both counts.

Prompt mix: 8 shared "system prompt" families of 2 pages each head
every prompt — 75% short (1 unique page) / 25% long (the TTFT-tail
driver) — so prefix-affinity has real sharing to exploit and the row's
``prefix_hit_rate`` must come out nonzero.

Quantized handoffs are ON for this harness (the `serve_handoff_quantize`
knob ships prefill->decode KV as int8 wire blocks): the row reports the
bytes the codec did NOT ship.

Run:  python benchmarks/serve_frontdoor.py [--connections 1000]
          [--duration 60] [--new-tokens 32] [--http-conns 256]
"""

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

try:
    from benchmarks._bench_util import percentiles as _percentiles
except ImportError:          # run as a script from benchmarks/
    from _bench_util import percentiles as _percentiles

PAGE_SIZE = 16
SYS_PAGES = 2                # shared "system prompt" head: 2 full pages
SYS_LEN = SYS_PAGES * PAGE_SIZE
SHORT_LEN = SYS_LEN + PAGE_SIZE        # 75% of the mix
LONG_LEN = SYS_LEN + 10 * PAGE_SIZE    # 25%: the TTFT-tail driver
MAX_SEQ = 256
N_FAMILIES = 8


def _requests(n, new_tokens, vocab=250):
    """Bimodal mix with shared-prefix heads: every prompt opens with one
    of N_FAMILIES fixed 2-page families, then a per-request tail."""
    fams = [[(f * 131 + j) % (vocab - 1) + 1 for j in range(SYS_LEN)]
            for f in range(N_FAMILIES)]
    reqs = []
    for i in range(n):
        plen = LONG_LEN if i % 4 == 0 else SHORT_LEN
        tail = [(i * 37 + j) % (vocab - 1) + 1
                for j in range(plen - SYS_LEN)]
        reqs.append({"prompt": fams[i % N_FAMILIES] + tail,
                     "max_new_tokens": new_tokens, "temperature": 0.8})
    return reqs


class _StreamStats:
    __slots__ = ("t0", "ttft", "token_ts", "error", "retries", "via")

    def __init__(self, via="handle"):
        self.t0 = 0.0
        self.ttft = None
        self.token_ts = []
        self.error = None
        self.retries = 0
        self.via = via


async def _drive(reqs, handle, connections, http_conns, port,
                 duration_s, ramp_s):
    """Closed loop at constant concurrency (cf. serve_disagg._drive):
    the first ``http_conns`` clients stream SSE over real HTTP, the
    rest through the DisaggHandle router in-process."""
    import aiohttp

    url = f"http://127.0.0.1:{port}/-/disagg/tiny"
    stats_all = []
    t_end = time.monotonic() + ramp_s + duration_s

    async def one_handle(req, st):
        st.t0 = time.monotonic()
        async for item in handle.stream(req):
            if "token" in item:
                now = time.monotonic()
                if st.ttft is None:
                    st.ttft = now - st.t0
                st.token_ts.append(now)
            elif "retry" in item:
                st.retries = item["retry"]

    async def one_http(session, req, st):
        st.t0 = time.monotonic()
        async with session.post(url, json=req) as resp:
            if resp.status != 200:
                raise RuntimeError(f"HTTP {resp.status}")
            async for raw in resp.content:
                line = raw.decode("utf-8").rstrip("\r\n")
                if not line.startswith("data:"):
                    continue
                d = json.loads(line[5:])
                if "token" in d:
                    now = time.monotonic()
                    if st.ttft is None:
                        st.ttft = now - st.t0
                    st.token_ts.append(now)
                elif "retry" in d:
                    st.retries = d["retry"]

    async def conn_loop(i, session):
        k = i
        while time.monotonic() < t_end:
            st = _StreamStats("http" if session is not None else "handle")
            stats_all.append(st)
            try:
                if session is not None:
                    await asyncio.wait_for(
                        one_http(session, reqs[k % len(reqs)], st),
                        timeout=900.0)
                else:
                    await asyncio.wait_for(
                        one_handle(reqs[k % len(reqs)], st),
                        timeout=900.0)
            except Exception as e:  # noqa: BLE001 - counted, not fatal
                st.error = f"{type(e).__name__}: {e}"
                await asyncio.sleep(0.5)   # no hot error spin
            k += connections

    conn = aiohttp.TCPConnector(limit=0)
    async with aiohttp.ClientSession(connector=conn) as session:
        tasks = []
        for i in range(connections):
            tasks.append(asyncio.ensure_future(
                conn_loop(i, session if i < http_conns else None)))
            await asyncio.sleep(0.002)     # submission spread
        await asyncio.gather(*tasks)
    return stats_all, t_end


def _summarize(stats, connections, http_conns, w0, w1):
    errors = [s for s in stats if s.error is not None]
    ok = [s for s in stats if s.error is None and s.t0 >= w0]
    ttfts = [s.ttft for s in ok if s.ttft is not None]
    tpots = []
    tokens = 0
    for s in stats:
        if s.error is None:
            tokens += sum(1 for ts in s.token_ts if w0 <= ts <= w1)
    for s in ok:
        if len(s.token_ts) > 1:
            tpots.append((s.token_ts[-1] - s.token_ts[0])
                         / (len(s.token_ts) - 1))
    t50, t99 = _percentiles(ttfts) if ttfts else (0.0, 0.0)
    p50, p99 = _percentiles(tpots) if tpots else (0.0, 0.0)
    row = {
        "metric": "serve_frontdoor_closed_loop",
        "connections": connections,
        "http_connections": http_conns,
        "streams": len(stats),
        "measured_streams": len(ok),
        "errors": len(errors),
        "retries": sum(s.retries for s in stats),
        "ttft_p50_ms": round(t50, 1),
        "ttft_p99_ms": round(t99, 1),
        "tpot_p50_ms": round(p50, 2),
        "tpot_p99_ms": round(p99, 1),
        "tokens_per_s": round(tokens / max(w1 - w0, 1e-9), 1),
        "window_s": round(w1 - w0, 1),
    }
    if errors:
        row["first_error"] = errors[0].error
    return row


def _slo_by_route():
    """Per-pool TTFT/TPOT verdicts from the SLO plane: every stream
    above closed an ingress root (the proxy's for HTTP connections,
    the router's for in-process ones) on its pool's route."""
    from ray_tpu.experimental.state.api import trace_stats
    out = {}
    try:
        for route, slot in (trace_stats().get("slo_by_route")
                            or {}).items():
            out[route] = {k: slot.get(k, 0) for k in
                          ("good", "violation", "ttft_violation",
                           "tpot_violation")}
    except Exception:
        pass
    return out


def _prefix_counters():
    """Cluster-wide prefix-affinity outcomes, with the driver's own
    (unflushed) counters folded in — the DisaggHandle router lives in
    this process."""
    from ray_tpu._private import runtime_metrics as rtm
    from ray_tpu.experimental.state.api import list_metrics

    by_outcome = {"hit": 0.0, "miss": 0.0, "evicted": 0.0}
    try:
        for r in list_metrics("ray_tpu_serve_prefix_hit"):
            o = r.get("tags", {}).get("outcome")
            if o in by_outcome:
                by_outcome[o] += r.get("value", 0.0)
    except Exception:
        pass
    local = (rtm.snapshot().get("ray_tpu_serve_prefix_hit")
             or {}).get("values") or {}
    for tagjson, val in local.items():
        try:
            o = json.loads(tagjson).get("outcome")
        except (ValueError, AttributeError):
            continue
        if o in by_outcome:
            # the driver's flusher may have published already; take the
            # larger reading rather than double counting
            by_outcome[o] = max(by_outcome[o], val)
    return by_outcome


def _handoff_savings():
    """Bytes the int8 wire codec kept off the transfer plane."""
    from ray_tpu.experimental.state.api import list_metrics
    saved = wire = 0.0
    try:
        for r in list_metrics():
            if r["name"] == "ray_tpu_serve_handoff_saved_bytes":
                saved += r.get("value", 0.0)
            elif r["name"] == "ray_tpu_serve_handoff_bytes" \
                    and r.get("sum"):
                wire += r["sum"]
    except Exception:
        pass
    out = {"handoff_saved_bytes": int(saved)}
    if saved and wire:
        out["handoff_saved_frac"] = round(saved / (saved + wire), 3)
    return out


def run_frontdoor(connections=1000, new_tokens=32, duration_s=60.0,
                  ramp_s=15.0, http_conns=256, slots=32, port=18299,
                  quantize=True):
    """One closed-loop run; returns its rows ([summary])."""
    import ray_tpu
    from ray_tpu import serve

    http_conns = min(connections, http_conns)
    reqs = _requests(connections, new_tokens)
    ray_tpu.init(num_cpus=4, object_store_memory=512 * 1024 * 1024,
                 system_config={
                     "actor_creation_timeout_s": 900.0,
                     "serve_handoff_quantize": bool(quantize),
                 })
    try:
        serve.start(serve.HTTPOptions(port=port))
        serve.run(serve.llm.build_app(
            preset="tiny", disaggregated=True, prefill_replicas=1,
            num_replicas=1, num_slots=2 * slots, paged=True,
            page_size=PAGE_SIZE, max_seq_len=MAX_SEQ,
            max_prompt_len=LONG_LEN + 8, block_size=8,
            max_concurrent_queries=2 * connections,
            warmup_prompt_lens=[SHORT_LEN, LONG_LEN],
            prefill_server_kwargs={
                "num_slots": 2, "kv_pool_pages": 1024,
                # room for all 8 families' heads plus churn
                "prefix_cache_pages": 8 * N_FAMILIES * SYS_PAGES,
            }))
        handle = serve.llm.disagg_handle("tiny")
        handle.pool_full_timeout_s = 600.0

        def drive(batch, conns, http_n, dur, ramp):
            return asyncio.run(_drive(batch, handle, conns, http_n,
                                      port, dur, ramp))

        # warm pass: jit shapes + the first advertisement round trip
        # (engine retain -> health-check advertise -> controller publish
        # -> router index) so the timed window measures steady state
        drive(reqs[:32], 32, 8, 4.0, 0.0)
        t0 = time.monotonic()
        stats, t_end = drive(reqs, connections, http_conns,
                             duration_s, ramp_s)
        row = _summarize(stats, connections, http_conns,
                         t0 + ramp_s, t_end)
        time.sleep(2.0)      # let the per-process flushers publish
        row["slo"] = _slo_by_route()
        pref = _prefix_counters()
        looked = sum(pref.values())
        row["prefix_hits"] = int(pref["hit"])
        row["prefix_misses"] = int(pref["miss"] + pref["evicted"])
        row["prefix_hit_rate"] = round(pref["hit"] / looked, 3) \
            if looked else 0.0
        row.update(_handoff_savings())
        row["bars"] = ("errors == 0; prefix_hit_rate > 0; "
                       "slo rows present for the decode route")
        print(json.dumps(row))
        sys.stdout.flush()
        return [row]
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--connections", type=int, default=1000)
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--ramp", type=float, default=15.0)
    ap.add_argument("--http-conns", type=int, default=256)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--port", type=int, default=18299)
    ap.add_argument("--no-quantize", action="store_true")
    args = ap.parse_args()
    run_frontdoor(args.connections, args.new_tokens, args.duration,
                  args.ramp, args.http_conns, args.slots, args.port,
                  quantize=not args.no_quantize)


if __name__ == "__main__":
    main()
