"""Serve no-op throughput/latency benchmark.

Counterpart of the reference's serve microbenchmark
(/root/reference/python/ray/serve/benchmarks/microbenchmark.py) and the
published numbers in doc/source/serve/performance.md:19-20 (1-2 ms handle
overhead; 3-4k no-op qps with 1 proxy + 8 replicas on 8 cores).

Prints one JSON line per scenario:
  {"metric": "serve_handle_qps", "value": ..., "p50_ms": ..., "p99_ms": ...}
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

try:
    from benchmarks._bench_util import percentiles as _percentiles
except ImportError:          # run as a script from benchmarks/
    from _bench_util import percentiles as _percentiles


def bench_handle(handle, n_warm=100, n=1000, concurrency=32):
    """Closed-loop with `concurrency` in-flight calls through the
    deployment handle (router + replica, no HTTP)."""
    import ray_tpu
    ray_tpu.get([handle.remote(i) for i in range(n_warm)], timeout=120)
    lats = []
    t0 = time.monotonic()
    done = 0
    # batched closed loop, like the reference serve microbenchmark's
    # asyncio.gather batches (serve/benchmarks/microbenchmark.py)
    while done < n:
        batch = min(concurrency, n - done)
        refs = [handle.remote(time.monotonic()) for _ in range(batch)]
        for r in refs:
            sent = ray_tpu.get(r, timeout=60)
            lats.append(time.monotonic() - sent)
        done += batch
    elapsed = time.monotonic() - t0
    p50, p99 = _percentiles(lats)
    return n / elapsed, p50, p99


def bench_overhead(handle, n_warm=50, n=300):
    """Concurrency-1 latency: p50 of a serve handle round-trip minus the
    p50 of a bare actor call — the serve stack's per-request overhead
    (the reference's 1-2 ms bar, doc/source/serve/performance.md:19)."""
    import ray_tpu

    @ray_tpu.remote
    class Bare:
        def noop(self, x):
            return x

    bare = Bare.remote()
    for _ in range(n_warm):
        ray_tpu.get(bare.noop.remote(1))
        ray_tpu.get(handle.remote(1), timeout=60)
    bare_lats, serve_lats = [], []
    for _ in range(n):
        t0 = time.monotonic()
        ray_tpu.get(bare.noop.remote(1))
        bare_lats.append(time.monotonic() - t0)
        t0 = time.monotonic()
        ray_tpu.get(handle.remote(1), timeout=60)
        serve_lats.append(time.monotonic() - t0)
    ray_tpu.kill(bare)
    bp50, _ = _percentiles(bare_lats)       # already milliseconds
    sp50, sp99 = _percentiles(serve_lats)
    return sp50 - bp50, bp50, sp50, sp99


def bench_http_floor(port, n=400, concurrency=16, path="/-/healthz"):
    """Same client load against a no-serve-hop proxy endpoint: what the
    aiohttp client+server pair alone costs on this box — the denominator
    for judging the serve overhead in bench_http.  ``/-/echo`` (POST +
    JSON both ways) is the apples-to-apples floor for the serve row."""
    import asyncio

    import aiohttp

    async def run():
        url = f"http://127.0.0.1:{port}{path}"
        lats = []
        post = path.endswith("echo")
        async with aiohttp.ClientSession() as sess:
            async def one():
                t0 = time.monotonic()
                req = (sess.post(url, json=1) if post
                       else sess.get(url))
                async with req as resp:
                    await resp.read()
                lats.append(time.monotonic() - t0)

            sem = asyncio.Semaphore(concurrency)

            async def bounded():
                async with sem:
                    await one()

            t0 = time.monotonic()
            await asyncio.gather(*[bounded() for _ in range(n)])
            elapsed = time.monotonic() - t0
        p50, p99 = _percentiles(lats)
        return n / elapsed, p50, p99

    return asyncio.run(run())


def bench_http(port, n_warm=50, n=500, concurrency=16):
    """aiohttp client closed-loop against the proxy."""
    import asyncio

    import aiohttp

    async def run():
        url = f"http://127.0.0.1:{port}/noop"
        lats = []
        async with aiohttp.ClientSession() as sess:
            async def one():
                t0 = time.monotonic()
                async with sess.post(url, json=1) as resp:
                    await resp.read()
                lats.append(time.monotonic() - t0)

            await asyncio.gather(*[one() for _ in range(n_warm)])
            lats.clear()
            t0 = time.monotonic()
            sem = asyncio.Semaphore(concurrency)

            async def bounded():
                async with sem:
                    await one()

            await asyncio.gather(*[bounded() for _ in range(n)])
            elapsed = time.monotonic() - t0
        p50, p99 = _percentiles(lats)
        return n / elapsed, p50, p99

    return asyncio.run(run())


def main():
    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=8, object_store_memory=256 * 1024 * 1024)

    @serve.deployment(max_concurrent_queries=64, num_replicas=1)
    def noop(x):
        return x

    handle = serve.run(noop.bind(),
                       http_options=serve.HTTPOptions(port=18230))
    qps, p50, p99 = bench_handle(handle)
    print(json.dumps({"metric": "serve_handle_qps", "value": round(qps, 1),
                      "p50_ms": round(p50, 2), "p99_ms": round(p99, 2),
                      "reference": "3-4k qps (8 replicas), 1-2ms overhead"}))
    overhead, bare_p50, serve_p50, serve_p99 = bench_overhead(handle)
    print(json.dumps({"metric": "serve_overhead_ms",
                      "value": round(overhead, 2),
                      "bare_actor_p50_ms": round(bare_p50, 2),
                      "serve_p50_ms": round(serve_p50, 2),
                      "serve_p99_ms": round(serve_p99, 2),
                      "reference": "1-2 ms serve overhead"}))
    floor_qps, fp50, fp99 = bench_http_floor(18230)
    print(json.dumps({"metric": "serve_http_floor_qps",
                      "value": round(floor_qps, 1),
                      "p50_ms": round(fp50, 2), "p99_ms": round(fp99, 2),
                      "note": "aiohttp client+server alone (healthz), "
                              "same box/concurrency — the transport "
                              "ceiling the serve rows sit under"}))
    echo_qps, ep50, _ = bench_http_floor(18230, path="/-/echo")
    print(json.dumps({"metric": "serve_http_echo_floor_qps",
                      "value": round(echo_qps, 1),
                      "p50_ms": round(ep50, 2),
                      "note": "POST + JSON both ways, no serve hop: the "
                              "apples-to-apples transport floor for "
                              "serve_http_qps"}))
    http_qps, hp50, hp99 = bench_http(18230)
    # single-core composition ceiling: every HTTP request serially costs
    # this one core the aiohttp POST+JSON transport work (1/echo_qps)
    # plus the full handle path (1/handle_qps) — the measured qps is
    # read against that ceiling, not against the multi-core reference bar
    ceiling = 1.0 / (1.0 / max(echo_qps, 1) + 1.0 / max(qps, 1))
    print(json.dumps({"metric": "serve_http_qps",
                      "value": round(http_qps, 1),
                      "p50_ms": round(hp50, 2), "p99_ms": round(hp99, 2),
                      "single_core_composition_ceiling_qps": round(ceiling, 1),
                      "pct_of_ceiling": round(100 * http_qps / ceiling, 1),
                      "reference": "~1.9k req/s microbenchmark (multi-core"
                                   " box); single core here — proxy, "
                                   "replica, client and daemons share it"}))
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
