"""Disaggregated prefill/decode serving load harness (docs/serve_disagg.md).

The MICROBENCH `serve_disagg` section: an interleaved same-box A/B of
colocated vs disaggregated LLM serving at EQUAL chip count under a
bimodal saturation mix, sustaining >= 1k concurrent streaming
connections through the real Serve stack (controller, replicas,
streaming generators, transfer-plane KV handoff).

  - colocated arm: 2 paged replicas, each prefilling AND decoding
    (the strongest single-pool baseline: slotless prefill-ahead, PR 4).
  - disaggregated arm: 1 prefill replica + 1 decode replica with 2x the
    per-replica slots (equal aggregate decode slots, equal replica
    count), KV handoffs shipped via ray_tpu.put / the PR 5 pull engine.

Why disaggregation wins p99 TTFT at saturation: a colocated engine's
prefill-ahead stalls the moment the KV pool fills — a queued prompt
cannot prefill until a RESIDENT request completes, so tail TTFT is
bound by decode turnover.  A prefill-only engine frees a request's
pages at export, so its prefill throughput never waits on decode; TTFT
is bound by prefill compute alone.  Aggregate tokens/s must stay within
10% (equal decode slots, the handoff riding idle host cycles).

Measured per stream (client side): TTFT (submit -> first token),
inter-token latency, per-stream decode block wall; plus the handoff
stage latencies from the replicas' telemetry.  One JSON line per row;
collect_microbench.py ingests these into MICROBENCH.json and
serve_disagg_deltas.

Run:  python benchmarks/serve_disagg.py [--connections 1000]
          [--rounds 1] [--new-tokens 16] [--slots 16]
"""

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

try:
    from benchmarks._bench_util import percentiles as _percentiles
except ImportError:          # run as a script from benchmarks/
    from _bench_util import percentiles as _percentiles

SHORT_LEN = 16               # bimodal prompt mix: 75% short ...
LONG_LEN = 192               # ... 25% long (the TTFT-tail driver)
PAGE_SIZE = 16
MAX_SEQ = 256


def _requests(n, new_tokens, vocab=250):
    reqs = []
    for i in range(n):
        plen = LONG_LEN if i % 4 == 0 else SHORT_LEN
        prompt = [(i * 37 + j) % (vocab - 1) + 1 for j in range(plen)]
        reqs.append({"prompt": prompt, "max_new_tokens": new_tokens,
                     "temperature": 0.8})
    return reqs


class _StreamStats:
    __slots__ = ("t0", "ttft", "token_ts", "error", "retries")

    def __init__(self):
        self.t0 = 0.0
        self.ttft = None
        self.token_ts = []
        self.error = None
        self.retries = 0


async def _drive_colocated(handle, worker, reqs, connections,
                           duration_s, ramp_s):
    from ray_tpu.serve.handle import _aget

    async def one(req, st):
        st.t0 = time.monotonic()
        gen = handle.remote_streaming(req)
        async for ref in gen:
            item = await _aget(worker, ref, timeout=600.0)
            if "token" in item:
                now = time.monotonic()
                if st.ttft is None:
                    st.ttft = now - st.t0
                st.token_ts.append(now)

    return await _drive(reqs, one, connections, duration_s, ramp_s)


async def _drive_disagg(handle, reqs, connections, duration_s, ramp_s):
    async def one(req, st):
        st.t0 = time.monotonic()
        async for item in handle.stream(req):
            if "token" in item:
                now = time.monotonic()
                if st.ttft is None:
                    st.ttft = now - st.t0
                st.token_ts.append(now)
            elif "retry" in item:
                st.retries = item["retry"]

    return await _drive(reqs, one, connections, duration_s, ramp_s)


async def _drive(reqs, one, connections, duration_s, ramp_s):
    """Closed loop at constant concurrency: each of ``connections``
    logical clients streams requests back-to-back until the window
    closes — steady state, where BOTH arms' prefill and decode work
    overlap (a one-shot burst lets the disaggregated prefill pool go
    idle after the drain, understating its throughput).  Streams in
    flight at the deadline run to completion but only in-window tokens
    count."""
    stats_all = []
    t_end = time.monotonic() + ramp_s + duration_s

    async def conn_loop(i):
        k = i
        while time.monotonic() < t_end:
            st = _StreamStats()
            stats_all.append(st)
            try:
                await asyncio.wait_for(one(reqs[k % len(reqs)], st),
                                       timeout=600.0)
            except Exception as e:  # noqa: BLE001 - counted, not fatal
                st.error = f"{type(e).__name__}: {e}"
                await asyncio.sleep(0.5)   # no hot error spin
            k += connections

    tasks = []
    for i in range(connections):
        tasks.append(asyncio.ensure_future(conn_loop(i)))
        await asyncio.sleep(0.002)         # ~2 s submission spread
    await asyncio.gather(*tasks)
    return stats_all, t_end


def _summarize(name, stats, connections, block_size, w0, w1):
    """Steady-state stats over the measurement window [w0, w1]:
    latency percentiles from streams STARTED in-window, tokens/s from
    token arrivals in-window (ramp and drain excluded)."""
    errors = [s for s in stats if s.error is not None]
    ok = [s for s in stats if s.error is None and s.t0 >= w0]
    # all lists in SECONDS; _percentiles converts to ms
    ttfts = [s.ttft for s in ok if s.ttft is not None]
    itls = []
    block_walls = []
    tokens = 0
    for s in stats:
        if s.error is not None:
            continue
        tokens += sum(1 for ts in s.token_ts if w0 <= ts <= w1)
    for s in ok:
        for a, b in zip(s.token_ts, s.token_ts[1:]):
            itls.append(b - a)
        if len(s.token_ts) > 1:
            # decode wall split over the stream's block dispatches:
            # tokens arrive in per-block bursts, so (last - first) /
            # nblocks is one block's wall time as the client feels it
            nblocks = max(1, -(-(len(s.token_ts) - 1) // block_size))
            block_walls.append(
                (s.token_ts[-1] - s.token_ts[0]) / nblocks)
    t50, t99 = _percentiles(ttfts) if ttfts else (0.0, 0.0)
    i50, i99 = _percentiles(itls) if itls else (0.0, 0.0)
    b50, _ = _percentiles(block_walls) if block_walls else (0.0, 0.0)
    row = {
        "metric": f"serve_disagg_{name}",
        "connections": connections,
        "streams": len(stats),
        "measured_streams": len(ok),
        "errors": len(errors),
        "retries": sum(s.retries for s in stats),
        "ttft_p50_ms": round(t50, 1),
        "ttft_p99_ms": round(t99, 1),
        "itl_p50_ms": round(i50, 2),
        "itl_p99_ms": round(i99, 1),
        "block_wall_p50_ms": round(b50, 1),
        "tokens_per_s": round(tokens / max(w1 - w0, 1e-9), 1),
        "window_s": round(w1 - w0, 1),
    }
    if errors:
        row["first_error"] = errors[0].error
    return row


def _engine_stats():
    """Per-replica engine snapshots (occupancy is the decode-waste
    telltale: junk-stepped slots past eos / between installs)."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.controller import REPLICA_PREFIX, SERVE_NAMESPACE

    out = {}
    try:
        for name, s in serve.status().items():
            rows = []
            for tag in s["replicas"]:
                try:
                    a = ray_tpu.get_actor(REPLICA_PREFIX + tag,
                                          namespace=SERVE_NAMESPACE)
                    st = ray_tpu.get(
                        a.handle_request.remote("stats", (), {}),
                        timeout=30)
                    rows.append({k: st[k] for k in
                                 ("batch_occupancy", "prefills",
                                  "requests_completed", "exports",
                                  "imports", "import_rejects")})
                except Exception:
                    pass
            out[name] = rows
    except Exception:
        pass
    return out


def _handoff_summary():
    """p50 handoff stage latencies/bytes from the replicas' telemetry
    (flushed to GCS; docs/observability.md)."""
    time.sleep(2.0)          # let the per-process flushers publish
    from ray_tpu.experimental.state.api import list_metrics
    out = {}
    for r in list_metrics():
        if r["name"] == "ray_tpu_serve_handoff_ms" and r.get("count"):
            out[r["tags"].get("stage", "?") + "_p50_ms"] = r.get("p50")
        if r["name"] == "ray_tpu_serve_handoff_bytes" and r.get("count"):
            out.setdefault("bytes_p50", r.get("p50"))
    stages = ("export_gather_p50_ms", "export_put_p50_ms",
              "import_pull_p50_ms", "import_admit_p50_ms")
    if any(k in out for k in stages):
        out["total_p50_ms"] = round(
            sum(out.get(k) or 0.0 for k in stages), 2)
    return out


def run_arm(mode, connections=1000, new_tokens=16, slots=16,
            block_size=8, duration_s=30.0, ramp_s=12.0, replicas=2):
    """One A/B arm in a fresh cluster; returns its summary row.

    Equal chip count both arms: ``replicas`` colocated replicas at
    ``slots`` each, vs ``replicas/2`` prefill + ``replicas/2`` decode
    replicas with the decode engines at ``2*slots`` (a decode-only
    chip hosts the whole chip's KV/compute — equal AGGREGATE decode
    slots, equal replica count)."""
    import ray_tpu
    from ray_tpu import serve

    reqs = _requests(connections, new_tokens)
    ray_tpu.init(num_cpus=2 * replicas,
                 object_store_memory=512 * 1024 * 1024,
                 system_config={"actor_creation_timeout_s": 900.0})
    try:
        serve.start()
        common = dict(preset="tiny", paged=True, page_size=PAGE_SIZE,
                      max_seq_len=MAX_SEQ, max_prompt_len=LONG_LEN + 8,
                      block_size=block_size,
                      max_concurrent_queries=2 * connections,
                      warmup_prompt_lens=[SHORT_LEN, LONG_LEN])
        if mode == "colocated":
            app = serve.llm.build_app(num_replicas=replicas,
                                      num_slots=slots, **common)
            handle = serve.run(app)
            from ray_tpu.runtime.core_worker import get_global_worker
            worker = get_global_worker()

            def drive(batch, conns, dur, ramp):
                return asyncio.run(_drive_colocated(
                    handle.stream, worker, batch, conns, dur, ramp))
        else:
            app = serve.llm.build_app(
                disaggregated=True, prefill_replicas=max(replicas // 2, 1),
                num_replicas=max(replicas // 2, 1),
                num_slots=2 * slots,
                prefill_server_kwargs={"num_slots": 2,
                                       "kv_pool_pages": 1024},
                **common)
            serve.run(app)
            handle = serve.llm.disagg_handle("tiny")
            handle.pool_full_timeout_s = 300.0

            def drive(batch, conns, dur, ramp):
                return asyncio.run(_drive_disagg(
                    handle, batch, conns, dur, ramp))

        # warm pass: lazily-compiled jit shapes (export/import page
        # buckets, burst fetch concats) must not pollute the timed run
        drive(reqs[:32], 32, 1.0, 0.0)
        t0 = time.monotonic()
        stats, t_end = drive(reqs, connections, duration_s, ramp_s)
        row = _summarize(mode, stats, connections, block_size,
                         t0 + ramp_s, t_end)
        if mode == "disaggregated":
            row["handoff"] = _handoff_summary()
        row["engines"] = _engine_stats()
        return row
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def run_ab(connections=1000, new_tokens=16, slots=16, block_size=8,
           rounds=1, replicas=2, duration_s=30.0):
    """Interleaved A/B; emits one JSON row per arm-round plus the
    aggregated comparison row, and returns all rows."""
    rows = []
    per_mode = {"colocated": [], "disaggregated": []}
    for _ in range(rounds):
        for mode in ("colocated", "disaggregated"):
            row = run_arm(mode, connections, new_tokens, slots,
                          block_size, duration_s=duration_s,
                          replicas=replicas)
            rows.append(row)
            per_mode[mode].append(row)
            print(json.dumps(row))
            sys.stdout.flush()

    def best(mode, key, lo=True):
        vals = [r[key] for r in per_mode[mode]]
        return min(vals) if lo else max(vals)

    handoff = next((r["handoff"] for r in per_mode["disaggregated"]
                    if r.get("handoff")), {})
    ab = {
        "metric": "serve_disagg_ab",
        "connections": connections,
        "ttft_p99_colocated_ms": best("colocated", "ttft_p99_ms"),
        "ttft_p99_disagg_ms": best("disaggregated", "ttft_p99_ms"),
        "ttft_p99_ratio": round(
            best("colocated", "ttft_p99_ms")
            / max(best("disaggregated", "ttft_p99_ms"), 1e-9), 2),
        "tokens_per_s_colocated": best("colocated", "tokens_per_s",
                                       lo=False),
        "tokens_per_s_disagg": best("disaggregated", "tokens_per_s",
                                    lo=False),
        "tokens_per_s_ratio": round(
            best("disaggregated", "tokens_per_s", lo=False)
            / max(best("colocated", "tokens_per_s", lo=False), 1e-9), 3),
        "handoff_total_p50_ms": handoff.get("total_p50_ms"),
        "decode_block_wall_p50_ms": best("disaggregated",
                                         "block_wall_p50_ms"),
        "errors": sum(r["errors"] for r in rows),
        "bars": "ttft_p99_ratio >= 2; tokens_per_s_ratio >= 0.9; "
                "handoff_total_p50_ms < decode_block_wall_p50_ms; "
                "errors == 0",
    }
    rows.append(ab)
    print(json.dumps(ab))
    sys.stdout.flush()
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--connections", type=int, default=1000)
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--rounds", type=int, default=1)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--duration", type=float, default=30.0)
    args = ap.parse_args()
    run_ab(args.connections, args.new_tokens, args.slots,
           args.block_size, args.rounds, args.replicas, args.duration)


if __name__ == "__main__":
    main()
