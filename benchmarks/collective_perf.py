"""Collective data-plane microbenchmark (docs/collective.md).

Interleaved same-box A/B of the rebuilt DCN collective group against the
pre-ISSUE-6 ring, so this box's VM-throttle drift hits both arms equally
(medians of per-round rates are reported):

* **new vs legacy allreduce** — allreduce at 1 KiB / 1 MiB / 64 MiB and
  world sizes 2/4/8 on a same-node group.  The new arm rides the data
  plane (segmented pipelined ring, shm channels between the colocated
  ranks, hierarchical reduce, recursive doubling below the small
  threshold); the legacy arm reproduces the old algorithm verbatim
  (``legacy_allreduce`` below: one blocking ``conn.call`` per ring step
  carrying a fully-pickled numpy copy over TCP loopback, zero overlap).
  The 64 MiB world-4 row is the >=3x acceptance bar.
* **zero-TCP assertion** — after the new-arm ops, every rank's
  ``ray_tpu_collective_tcp_bytes_total`` must read exactly 0 on the
  same-node group (the shm-transport bar).
* **broadcast 64 MiB** — new (object-transfer-plane route) vs legacy
  (store-and-forward ring).
* **multi-source broadcast** — a 4-rank group spread over 4 simulated
  nodes (cluster_utils), rank starts staggered so completed ranks
  become additional sources: per-rank ``ray_tpu_pull_sources``
  telemetry must show >= 2 distinct sources used by at least one rank
  (the ROADMAP item 4 weight-sync shape).
* **quantized A/B + overlap** (``--quant``, the ``collective_quant``
  MICROBENCH section) — same-box ws4 groups with shm DISABLED (all
  segments on TCP).  The fp32 vs ``quantize="int8"`` interleaved A/B
  at 1/64 MiB runs under ``collective_sim_dcn_mbps`` pacing (a modeled
  bytes-limited DCN link — the codec's target regime; unpaced loopback
  only measures encode CPU vs kernel memcpy); the 64 MiB speedup row
  is the >=2x bar.  The async-overlap probe runs unpaced —
  ``allreduce_async`` behind a calibrated synthetic backward pass,
  reporting the fraction of ring time hidden (>=50% bar).

Run on an IDLE box (MICROBENCH policy): ratios are load-sensitive.

Prints JSON lines (names are collect_microbench delta keys):
  {"name": "allreduce <size> ws<N> legacy", "mb_per_s": ...}
  {"name": "allreduce <size> ws<N> new",    "mb_per_s": ...}
  {"name": "allreduce <size> ws<N> speedup", "speedup": ...}
  {"name": "collective tcp bytes same-node", "tcp_bytes": 0.0}
  {"name": "broadcast 64MiB ws4 legacy|new", "mb_per_s": ...}
  {"name": "bcast 64MiB multi-source", "nsources_max": ..., ...}
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("RAY_TPU_TELEMETRY", "1")

ROUNDS = int(os.environ.get("COLLECTIVE_BENCH_ROUNDS", "3"))
WORLDS = [int(w) for w in
          os.environ.get("COLLECTIVE_BENCH_WORLDS", "2,4,8").split(",")]
SIZES = [("1KiB", 256), ("1MiB", 256 * 1024), ("64MiB", 16 * 1024 * 1024)]
SKIP_MULTINODE = os.environ.get("COLLECTIVE_BENCH_SKIP_MULTINODE") == "1"

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402


@ray_tpu.remote
class BenchRank:
    def __init__(self, world, rank, name, cfg=None):
        from ray_tpu._private.config import CONFIG
        from ray_tpu.util import collective as col
        CONFIG.update(cfg or {})
        self.col = col
        self.name = name
        self.rank = rank
        self.world = world
        col.init_collective_group(world, rank, group_name=name,
                                  timeout=120.0)
        self.x = None

    def prep(self, nelems):
        self.x = np.random.RandomState(self.rank).uniform(
            1.0, 2.0, nelems).astype(np.float32)
        return True

    def allreduce_new(self):
        t0 = time.perf_counter()
        out = self.col.allreduce(self.x, self.name)
        dt = time.perf_counter() - t0
        return dt, float(out[0])

    def allreduce_quant(self):
        t0 = time.perf_counter()
        out = self.col.allreduce(self.x, self.name, quantize="int8")
        dt = time.perf_counter() - t0
        return dt, float(out[0])

    def overlap_probe(self, rounds):
        """Async allreduce behind a synthetic backward pass, per-round
        (comm_ms, blocked_wait_ms) from the handle's own clocks.  The
        backward is modeled as a host SLEEP sized ~2x one sync op: in
        the regime sync_gradients(async_op=True) targets, backward
        runs on the accelerator and the host is idle — which is also
        what lets the probe discriminate cleanly on a shared-CPU box
        (a host busy-loop would starve the async worker through the
        GIL and measure scheduler luck, not the engine).  A broken
        engine that only ran the op inside result() would still show
        wait ~= comm, i.e. hidden ~= 0."""
        import statistics as st
        t0 = time.perf_counter()
        self.col.allreduce(self.x, self.name)
        t_comm = time.perf_counter() - t0
        comm, wait = [], []
        for _ in range(rounds):
            h = self.col.allreduce_async(self.x, self.name)
            time.sleep(2.0 * t_comm)   # the device-side backward
            t0 = time.perf_counter()
            h.result(timeout=300)
            wait.append((time.perf_counter() - t0) * 1000.0)
            comm.append(h.comm_ms() or 0.0)
        return st.median(comm), st.median(wait)

    def broadcast_new(self, src, stagger_s=0.0):
        if stagger_s and self.rank != src:
            time.sleep(stagger_s * self.rank)
        t0 = time.perf_counter()
        out = self.col.broadcast(self.x, src, self.name)
        dt = time.perf_counter() - t0
        return dt, float(out[0])

    # ------------------------------------------------------------ legacy
    # The pre-ISSUE-6 ring, verbatim: every step is one blocking
    # conn.call("msg") carrying a fully-pickled numpy chunk copy over
    # TCP, recv via the mailbox — send -> recv -> reduce serialized,
    # ~4 copies per tensor, loopback TCP between colocated ranks.
    def _legacy_send(self, g, peer, tag, data):
        g._conn_to(peer).call(
            "msg", {"src": self.rank, "tag": tag, "data": data},
            timeout=120.0)

    def _legacy_recv(self, g, peer, tag):
        return g._mailbox.get(peer, tag, 120.0)

    def legacy_allreduce(self):
        from ray_tpu.util.collective.collective import _get
        g = _get(self.name)
        x = self.x
        n = self.world
        t0 = time.perf_counter()
        if n == 1:
            return 0.0, float(x[0])
        self._lseq = getattr(self, "_lseq", 0) + 1
        tag = f"lg:{self._lseq}"   # unsequenced: mailbox keeps it
        flat = x.reshape(-1).astype(x.dtype, copy=True)
        chunks = np.array_split(flat, n)
        nxt, prv = (self.rank + 1) % n, (self.rank - 1) % n
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            self._legacy_send(g, nxt, f"{tag}:rs{step}", chunks[send_idx])
            incoming = self._legacy_recv(g, prv, f"{tag}:rs{step}")
            chunks[recv_idx] = np.add(chunks[recv_idx], incoming)
        for step in range(n - 1):
            send_idx = (self.rank - step + 1) % n
            recv_idx = (self.rank - step) % n
            self._legacy_send(g, nxt, f"{tag}:ag{step}", chunks[send_idx])
            chunks[recv_idx] = self._legacy_recv(g, prv, f"{tag}:ag{step}")
        out = np.concatenate(chunks).reshape(x.shape)
        dt = time.perf_counter() - t0
        return dt, float(out[0])

    def legacy_broadcast(self, src):
        from ray_tpu.util.collective.collective import _get
        g = _get(self.name)
        n = self.world
        t0 = time.perf_counter()
        self._lseq = getattr(self, "_lseq", 0) + 1
        tag = f"lgb:{self._lseq}"
        if self.rank == src:
            out = self.x
        else:
            out = self._legacy_recv(g, (self.rank - 1) % n, tag)
        nxt = (self.rank + 1) % n
        if nxt != src:
            self._legacy_send(g, nxt, tag, out)
        dt = time.perf_counter() - t0
        return dt, float(np.asarray(out)[0])

    def metric(self, name):
        from ray_tpu._private import runtime_metrics as rtm
        rec = rtm.snapshot().get(name)
        return rec["values"] if rec else None

    def destroy(self):
        self.col.destroy_collective_group(self.name)
        return True


def emit(row):
    print(json.dumps(row), flush=True)


def median_rate(times_s, nbytes):
    return round(nbytes / max(1e-9, statistics.median(times_s)) / 2**20, 1)


def bench_same_node():
    ray_tpu.init(num_cpus=max(WORLDS) + 2,
                 object_store_memory=1024 * 1024 * 1024)
    try:
        for world in WORLDS:
            name = f"bench-{world}"
            ranks = [BenchRank.remote(world, r, name) for r in range(world)]
            for label, nelems in SIZES:
                nbytes = nelems * 4
                ray_tpu.get([r.prep.remote(nelems) for r in ranks],
                            timeout=120)
                new_t, old_t = [], []
                for _ in range(ROUNDS):
                    # interleaved A/B: box drift hits both arms equally
                    outs = ray_tpu.get(
                        [r.allreduce_new.remote() for r in ranks],
                        timeout=600)
                    new_t.append(max(dt for dt, _ in outs))
                    outs = ray_tpu.get(
                        [r.legacy_allreduce.remote() for r in ranks],
                        timeout=600)
                    old_t.append(max(dt for dt, _ in outs))
                if label == "1KiB":
                    # latency regime: MB/s rounds to noise
                    old_l = statistics.median(old_t) * 1000.0
                    new_l = statistics.median(new_t) * 1000.0
                    emit({"name": f"allreduce {label} ws{world} legacy",
                          "lat_ms": round(old_l, 2)})
                    emit({"name": f"allreduce {label} ws{world} new",
                          "lat_ms": round(new_l, 2)})
                    emit({"name": f"allreduce {label} ws{world} speedup",
                          "speedup": round(old_l / max(1e-6, new_l), 2)})
                    continue
                new_r = median_rate(new_t, nbytes)
                old_r = median_rate(old_t, nbytes)
                emit({"name": f"allreduce {label} ws{world} legacy",
                      "mb_per_s": old_r})
                emit({"name": f"allreduce {label} ws{world} new",
                      "mb_per_s": new_r})
                emit({"name": f"allreduce {label} ws{world} speedup",
                      "speedup": round(new_r / max(0.001, old_r), 2)})
            if world == 4:
                # broadcast 64 MiB A/B on the same 4-rank group
                nelems = SIZES[-1][1]
                nbytes = nelems * 4
                ray_tpu.get([r.prep.remote(nelems) for r in ranks],
                            timeout=120)
                new_t, old_t = [], []
                for _ in range(ROUNDS):
                    outs = ray_tpu.get(
                        [r.broadcast_new.remote(0) for r in ranks],
                        timeout=600)
                    new_t.append(max(dt for dt, _ in outs))
                    outs = ray_tpu.get(
                        [r.legacy_broadcast.remote(0) for r in ranks],
                        timeout=600)
                    old_t.append(max(dt for dt, _ in outs))
                emit({"name": "broadcast 64MiB ws4 legacy",
                      "mb_per_s": median_rate(old_t, nbytes)})
                emit({"name": "broadcast 64MiB ws4 new",
                      "mb_per_s": median_rate(new_t, nbytes)})
            if world == max(WORLDS):
                tcp = 0.0
                for r in ranks:
                    v = ray_tpu.get(r.metric.remote(
                        "ray_tpu_collective_tcp_bytes_total"), timeout=60)
                    tcp += v["{}"] if v else 0.0
                # same-node group: the shm transport must have moved
                # EVERY collective byte (legacy arm bypasses the
                # counter by construction)
                emit({"name": "collective tcp bytes same-node",
                      "tcp_bytes": tcp, "bar": "== 0"})
            ray_tpu.get([r.destroy.remote() for r in ranks], timeout=120)
            for r in ranks:
                ray_tpu.kill(r)
    finally:
        ray_tpu.shutdown()


SIM_DCN_MBPS = float(os.environ.get("COLLECTIVE_BENCH_DCN_MBPS", "6"))


def bench_quant_overlap():
    """The ``collective_quant`` MICROBENCH section (``--quant``): ws4
    same-box groups with the shm transport DISABLED so every segment
    rides TCP.

    The fp32-vs-int8 A/B runs with ``collective_sim_dcn_mbps`` pacing
    every published segment to a modeled bytes-limited DCN link.  On
    this box the unthrottled loopback "wire" is itself CPU (4 ranks
    share the cores), so an unpaced A/B only measures encode cost vs
    kernel memcpy — the codec's target regime is the one where DCN
    bytes are the bottleneck, and the pacing puts both arms there
    while charging each arm its own ENCODED byte count.  The 64 MiB
    speedup row is the >=2x acceptance bar.

    The overlap probe runs on an UNPACED group (overlap is about
    hiding real ring time behind compute): allreduce_async behind a
    calibrated synthetic backward, reporting the fraction of ring time
    hidden (>=0.5 bar)."""
    world = 4
    ray_tpu.init(num_cpus=world + 2,
                 object_store_memory=1024 * 1024 * 1024)
    try:
        cfg = {"collective_shm_enabled": False,
               "collective_flat_shm": False,
               "collective_quant_min_bytes": 64 * 1024,
               "collective_sim_dcn_mbps": SIM_DCN_MBPS}
        ranks = [BenchRank.remote(world, r, "bench-quant", cfg=cfg)
                 for r in range(world)]
        for label, nelems in [("1MiB", 256 * 1024),
                              ("64MiB", 16 * 1024 * 1024)]:
            nbytes = nelems * 4
            ray_tpu.get([r.prep.remote(nelems) for r in ranks],
                        timeout=120)
            fp_t, q_t = [], []
            for _ in range(ROUNDS):
                # interleaved A/B: box drift hits both arms equally
                outs = ray_tpu.get(
                    [r.allreduce_new.remote() for r in ranks],
                    timeout=600)
                fp_t.append(max(dt for dt, _ in outs))
                outs = ray_tpu.get(
                    [r.allreduce_quant.remote() for r in ranks],
                    timeout=600)
                q_t.append(max(dt for dt, _ in outs))
            fp_r = median_rate(fp_t, nbytes)
            q_r = median_rate(q_t, nbytes)
            emit({"name": f"allreduce {label} ws{world} sim-dcn fp32",
                  "mb_per_s": fp_r, "sim_dcn_mbps": SIM_DCN_MBPS})
            emit({"name": f"allreduce {label} ws{world} sim-dcn int8",
                  "mb_per_s": q_r, "sim_dcn_mbps": SIM_DCN_MBPS})
            row = {"name": f"allreduce {label} ws{world} quant speedup",
                   "speedup": round(q_r / max(0.001, fp_r), 2)}
            if label == "64MiB":
                row["bar"] = ">= 2.0"
            emit(row)
        ray_tpu.get([r.destroy.remote() for r in ranks], timeout=120)
        for r in ranks:
            ray_tpu.kill(r)

        # overlap probe on an UNPACED group, 8 MiB: large enough that
        # the ring dwarfs per-op bookkeeping, small enough for quick
        # rounds
        cfg.pop("collective_sim_dcn_mbps")
        ranks = [BenchRank.remote(world, r, "bench-overlap", cfg=cfg)
                 for r in range(world)]
        ray_tpu.get([r.prep.remote(2 * 1024 * 1024) for r in ranks],
                    timeout=120)
        outs = ray_tpu.get(
            [r.overlap_probe.remote(max(5, ROUNDS)) for r in ranks],
            timeout=900)
        # min over ranks: the bar holds only if EVERY rank hides
        fracs = [max(0.0, (c - w) / c) if c > 0 else 0.0
                 for c, w in outs]
        emit({"name": "allreduce 8MiB ws4 overlap hidden-frac",
              "hidden_frac": round(min(fracs), 3),
              "comm_ms": round(max(c for c, _ in outs), 1),
              "wait_ms": round(max(w for _, w in outs), 1),
              "bar": ">= 0.5"})
        ray_tpu.get([r.destroy.remote() for r in ranks], timeout=120)
        for r in ranks:
            ray_tpu.kill(r)
    finally:
        ray_tpu.shutdown()


def bench_multi_source():
    """4 ranks on 4 simulated nodes: the cross-node (DCN) regime.
    Interleaved allreduce A/B (pipelined zero-copy TCP ring vs the
    legacy blocking ring — the transport-apples comparison the >=3x
    bar describes), then the staggered multi-source broadcast
    (>= 2 distinct sources observed)."""
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster(head_resources={"CPU": 4, "colrank0": 1},
                      object_store_memory=512 * 1024 * 1024)
    try:
        for i in range(1, 4):
            cluster.add_node(resources={"CPU": 4, f"colrank{i}": 1},
                             object_store_memory=512 * 1024 * 1024)
        ray_tpu.init(address=cluster.address)
        nelems = 16 * 1024 * 1024  # 64 MiB float32
        ranks = []
        for i in range(4):
            ranks.append(BenchRank.options(
                resources={f"colrank{i}": 1}).remote(4, i, "ms-bcast"))
        ray_tpu.get([r.prep.remote(nelems) for r in ranks], timeout=300)
        nbytes = nelems * 4
        new_t, old_t = [], []
        for _ in range(ROUNDS):
            outs = ray_tpu.get([r.allreduce_new.remote() for r in ranks],
                               timeout=900)
            new_t.append(max(dt for dt, _ in outs))
            outs = ray_tpu.get(
                [r.legacy_allreduce.remote() for r in ranks],
                timeout=900)
            old_t.append(max(dt for dt, _ in outs))
        new_r = median_rate(new_t, nbytes)
        old_r = median_rate(old_t, nbytes)
        emit({"name": "allreduce 64MiB ws4 multinode legacy",
              "mb_per_s": old_r})
        emit({"name": "allreduce 64MiB ws4 multinode new",
              "mb_per_s": new_r})
        emit({"name": "allreduce 64MiB ws4 multinode speedup",
              "speedup": round(new_r / max(0.001, old_r), 2)})
        t0 = time.perf_counter()
        ray_tpu.get([r.broadcast_new.remote(0, stagger_s=1.0)
                     for r in ranks], timeout=900)
        dt = time.perf_counter() - t0
        nsources = []
        for r in ranks[1:]:
            v = ray_tpu.get(
                r.metric.remote("ray_tpu_pull_sources"), timeout=60)
            if v:
                buckets = v["{}"]["buckets"]
                # highest non-empty bucket boundary ~ max sources seen
                nsources.append(max(float(k) if k != "+Inf" else 99.0
                                    for k in buckets))
        emit({"name": "bcast 64MiB multi-source",
              "ranks": 4, "wall_s": round(dt, 2),
              "nsources_max": max(nsources) if nsources else 0,
              "nsources_per_rank": nsources,
              "bar": "nsources_max >= 2"})
        ray_tpu.get([r.destroy.remote() for r in ranks], timeout=120)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def main():
    if "--quant" in sys.argv[1:]:
        bench_quant_overlap()
        return
    bench_same_node()
    if not SKIP_MULTINODE:
        bench_multi_source()


if __name__ == "__main__":
    main()
