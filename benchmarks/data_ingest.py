"""Bulk-ingest throughput benchmark for ray_tpu.data.

Counterpart of the reference's DummyTrainer ingest benchmark
(doc/source/ray-air/benchmarks.rst:35-46: 0.51 GiB/s on one m5.4xlarge,
scaling to 52.6 GiB/s on 20 nodes): synthesize a multi-block numpy
dataset through read tasks, then measure the consumer-side rate of
streaming every block through iter_batches (read + shm round-trip,
including spill/restore once the working set exceeds the store).

Prints one JSON line: {"metric": "data_ingest_gib_per_s", ...}.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main(total_gib: float = 2.0, block_mib: int = 128):
    import ray_tpu
    from ray_tpu.data.datasource import ReadTask

    ray_tpu.init(num_cpus=4,
                 object_store_memory=1024 * 1024 * 1024)
    rows_per_block = block_mib * 1024 * 1024 // 8  # float64 elements
    n_blocks = max(1, int(total_gib * 1024 / block_mib))

    def make_block(i):
        def read():
            return [np.full(rows_per_block, i, dtype=np.float64)]
        return ReadTask(read, num_rows=1)

    from ray_tpu.data.dataset import Dataset, ExecutionPlan
    ds = Dataset(ExecutionPlan(
        read_tasks=[make_block(i) for i in range(n_blocks)]))

    t0 = time.monotonic()
    consumed = 0
    for batch in ds.iter_batches(batch_size=None):
        for arr in batch if isinstance(batch, list) else [batch]:
            consumed += getattr(arr, "nbytes", 0)
    elapsed = time.monotonic() - t0
    gib = consumed / 1024**3
    print(json.dumps({
        "metric": "data_ingest_gib_per_s",
        "value": round(gib / elapsed, 3),
        "gib": round(gib, 2),
        "seconds": round(elapsed, 2),
        "blocks": n_blocks,
        "reference": "0.51 GiB/s single m5.4xlarge (benchmarks.rst:35)",
    }))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
