"""Vision north-star benchmark: ResNet-50 training img/s on the chip.

BASELINE.md north-star row 2: "JaxTrainer ResNet-50/CIFAR-10 (single-host
DP) img/s vs GPU table" — the reference's GPU image-training table
(doc/source/ray-air/benchmarks.rst:158-174) measures a torch trainer at
40.7 img/s on 1 GPU and 746.3 img/s on 16 GPUs (224px images).  Two rows
here, both through the repo's sharded vision train step
(train/step.py make_vision_train — the same step JaxTrainer workers run):

  - resnet50_cifar10:        32px/10-class, the north-star config.
  - resnet50_imagenet_shape: 224px/1000-class synthetic, the row directly
                             comparable to the reference's GPU table.

  python benchmarks/vision_perf.py [--steps 30] [--batch 256]

Prints one JSON line per row.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def bench(image_px: int, num_classes: int, batch: int, steps: int,
          warmup: int, label: str, reference: str):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.resnet import ResNet50
    from ray_tpu.parallel import MeshConfig, build_mesh
    from ray_tpu.train.step import OptimizerConfig, make_vision_train

    mesh = build_mesh(MeshConfig(data=-1))
    model = ResNet50(num_classes=num_classes, small_inputs=image_px <= 64)
    rng = np.random.default_rng(0)
    batch_data = {
        "image": jnp.asarray(rng.standard_normal(
            (batch, image_px, image_px, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, num_classes, (batch,)),
                             jnp.int32),
    }
    init_fn, step_fn, _, _ = make_vision_train(
        model, mesh, OptimizerConfig(warmup_steps=10, decay_steps=1000),
        example_batch=batch_data)
    state = init_fn(jax.random.PRNGKey(0), batch_data)
    for _ in range(warmup):
        state, metrics = step_fn(state, batch_data)
    # fence via D2H read: on the axon tunnel block_until_ready returns
    # early; a host fetch forces the chain (same pattern as bench.py)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch_data)
    final_loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    dev = jax.devices()[0]
    return {
        "metric": f"vision_{label}",
        "model": "resnet50",
        "image_px": image_px,
        "num_classes": num_classes,
        "batch": batch,
        "img_per_s": round(batch / dt, 1),
        "step_ms": round(dt * 1e3, 2),
        "final_loss": round(final_loss, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "n_chips": mesh.size,
        "reference": reference,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=None)
    args = ap.parse_args()

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        rows = [
            bench(32, 10, args.batch or 1024, args.steps, 3,
                  "resnet50_cifar10",
                  "north-star config (BASELINE.md row 2); 32px has no "
                  "direct reference number"),
            bench(224, 1000, args.batch or 256, args.steps, 3,
                  "resnet50_imagenet_shape",
                  "reference GPU table benchmarks.rst:166: 40.7 img/s "
                  "on 1 GPU (g4dn, torch), 746.3 img/s on 16 GPUs; this "
                  "row is synthetic device-resident data (no input "
                  "pipeline), pure train-step throughput"),
        ]
    else:   # CI smoke: tiny shapes, throughput not meaningful
        rows = [bench(32, 10, args.batch or 16, 3, 1,
                      "resnet50_cifar10_smoke", "cpu smoke")]
    for row in rows:
        print(json.dumps(row))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
