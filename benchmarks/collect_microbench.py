"""Assemble MICROBENCH.json from the individual benchmark programs.

Counterpart of the reference's release/benchmarks result collection:
runs the core ops/s suite (ray_perf), the Serve qps/latency/overhead
benchmark, and the Data bulk-ingest benchmark — each in its own process
so daemons can't leak between sections — and merges their JSON output
with the scale-envelope numbers recorded by tests/test_scale_envelope.py.

Usage:  python benchmarks/collect_microbench.py [-o MICROBENCH.json]
"""

import argparse
import json
import os
import platform
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_json_lines(cmd, timeout=900):
    # no PYTHONPATH: it leaks into the TPU tunnel's helper subprocesses
    # and breaks the axon backend; every benchmark script self-inserts
    # the repo root into sys.path instead
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=dict(os.environ), cwd=REPO)
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{") or line.startswith("["):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    if proc.returncode != 0 and not rows:
        raise RuntimeError(f"{cmd}: rc={proc.returncode}\n{proc.stderr[-2000:]}")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output",
                    default=os.path.join(REPO, "MICROBENCH.json"))
    args = ap.parse_args()

    try:
        import psutil
        mem_gb = round(psutil.virtual_memory().total / 1024**3, 1)
        cpus = psutil.cpu_count(logical=False) or os.cpu_count()
    except ImportError:
        mem_gb = None
        cpus = os.cpu_count()

    out = {
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "host": {"cpus": os.cpu_count(), "physical_cpus": cpus,
                 "memory_gb": mem_gb, "platform": platform.platform()},
        "note": "reference microbenchmark runs on 16+ core machines; this "
                "box has 1 physical core — per-core comparisons only",
    }

    print("[collect] core ops/s suite (ray_perf)...", flush=True)
    core = _run_json_lines(
        [sys.executable, "-m", "ray_tpu._private.ray_perf"])
    out["core"] = core[-1] if core and isinstance(core[-1], list) else core

    print("[collect] serve qps/latency/overhead...", flush=True)
    out["serve"] = _run_json_lines(
        [sys.executable, os.path.join(REPO, "benchmarks", "serve_qps.py")])

    print("[collect] data bulk ingest...", flush=True)
    out["data"] = _run_json_lines(
        [sys.executable, os.path.join(REPO, "benchmarks", "data_ingest.py")])

    print("[collect] LLM serving (continuous batching, real chip)...",
          flush=True)
    out["serve_llm"] = _run_json_lines(
        [sys.executable, os.path.join(REPO, "benchmarks", "serve_llm.py"),
         "--slots", "32", "--requests", "128"], timeout=2400)

    # scale envelope: written by tests/test_scale_envelope.py when it runs;
    # keep the previous numbers if present
    try:
        with open(args.output) as f:
            prev = json.load(f)
        if "envelope" in prev:
            out["envelope"] = prev["envelope"]
    except (OSError, ValueError):
        pass

    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    print(f"[collect] wrote {args.output}")


if __name__ == "__main__":
    main()
