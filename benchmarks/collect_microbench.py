"""Assemble MICROBENCH.json from the individual benchmark programs.

Counterpart of the reference's release/benchmarks result collection:
each registered section (core ops/s, serve qps, data ingest, LLM
serving, RL, vision) runs in its own process so daemons can't leak
between sections, and their JSON outputs are merged into one file.
Sections that a run does NOT regenerate — because `--only` skipped
them, their script produced no rows, or they were written by another
program (the scale envelope from tests/test_scale_envelope.py) — are
preserved verbatim from the existing output file.

Usage:  python benchmarks/collect_microbench.py [-o MICROBENCH.json]
                                                [--only SECTION ...]
"""

import argparse
import json
import os
import platform
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_json_lines(cmd, timeout=900):
    # no PYTHONPATH: it leaks into the TPU tunnel's helper subprocesses
    # and breaks the axon backend; every benchmark script self-inserts
    # the repo root into sys.path instead
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, env=dict(os.environ), cwd=REPO)
    rows = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{") or line.startswith("["):
            try:
                rows.append(json.loads(line))
            except ValueError:
                pass
    if proc.returncode != 0 and not rows:
        raise RuntimeError(f"{cmd}: rc={proc.returncode}\n{proc.stderr[-2000:]}")
    return rows, proc.returncode


# Every benchmark program the collector owns, in run order.  Adding a
# section here is the ONLY step needed for it to survive refreshes: any
# section present in the existing output file that the current run does
# not regenerate is carried over verbatim (merge-preserve), so a partial
# `--only` refresh can never silently drop another program's numbers.
SECTIONS = {
    "core": dict(cmd=[sys.executable, "-m", "ray_tpu._private.ray_perf"],
                 timeout=900, last_list=True),
    "serve": dict(cmd=[sys.executable,
                       os.path.join(REPO, "benchmarks", "serve_qps.py")],
                  timeout=900),
    "data": dict(cmd=[sys.executable,
                      os.path.join(REPO, "benchmarks", "data_ingest.py")],
                 timeout=900),
    "streaming": dict(cmd=[sys.executable,
                           os.path.join(REPO, "benchmarks",
                                        "streaming_perf.py")],
                      timeout=600),
    # compiled static graphs (docs/compiled_dag.md): interleaved A/B of
    # a 3-stage actor chain, compiled (shm channels, zero per-call task
    # submission) vs classic dag.execute(); the speedup row is the >=5x
    # bar and the shm-growth row the ==0 slot-reuse bar
    "compiled_dag": dict(cmd=[sys.executable,
                              os.path.join(REPO, "benchmarks",
                                           "compiled_dag_perf.py")],
                         timeout=600),
    # bulk data plane (docs/object_transfer.md): interleaved same-box A/B
    # of a 64 MiB cross-node pull — pipelined zero-copy engine vs the
    # legacy serial algorithm (>=3x bar), striped 2-source vs 1 (>1x
    # bar), shm growth == object size (zero-copy bar), and the
    # prefetch-overlap task-e2e saving
    "object_transfer": dict(cmd=[sys.executable,
                                 os.path.join(REPO, "benchmarks",
                                              "object_transfer_perf.py")],
                            timeout=900),
    # collective data plane (docs/collective.md): interleaved same-box
    # A/B of the rebuilt DCN group (pipelined shm/TCP ring,
    # hierarchical) vs the legacy blocking ring at 1KiB/1MiB/64MiB and
    # world sizes 2/4/8 (>=3x bar at 64MiB ws4), the zero-TCP-bytes
    # same-node bar, and the multi-source 64MiB broadcast (>=2 sources)
    "collective": dict(cmd=[sys.executable,
                            os.path.join(REPO, "benchmarks",
                                         "collective_perf.py")],
                       timeout=2400),
    # quantized collective + backward overlap (docs/collective.md): ws4
    # group with the shm transport disabled so every segment rides
    # loopback TCP (the DCN regime the int8 codec targets) — fp32 vs
    # quantize="int8" interleaved A/B at 1/64 MiB (>=2x bar at 64 MiB)
    # and the allreduce_async overlap probe (>=50% of ring time hidden
    # behind a calibrated synthetic backward)
    "collective_quant": dict(cmd=[sys.executable,
                                  os.path.join(REPO, "benchmarks",
                                               "collective_perf.py"),
                                  "--quant"],
                             timeout=1200),
    # always-on runtime telemetry cost guard (docs/observability.md):
    # interleaved same-box A/B of task throughput with
    # RAY_TPU_TELEMETRY=0 vs 1; the overhead_pct row is the <=3% bar
    "telemetry": dict(cmd=[sys.executable,
                           os.path.join(REPO, "benchmarks",
                                        "telemetry_overhead.py")],
                      timeout=900),
    # cluster event plane cost guard (docs/observability.md):
    # interleaved same-box A/B of task throughput with RAY_TPU_EVENTS=0
    # vs 1 (telemetry pinned on in both arms); the events_overhead row
    # carries the same <=3% bar as the telemetry plane
    "events": dict(cmd=[sys.executable,
                        os.path.join(REPO, "benchmarks",
                                     "telemetry_overhead.py"),
                        "--events"],
                   timeout=900),
    # training performance plane cost guard (docs/observability.md):
    # interleaved same-box A/B of a fully-clocked ms-scale step loop
    # with RAY_TPU_STEP_STATS=0 vs 1 (telemetry + events pinned on);
    # the step_stats_overhead row carries the same <=3% bar.  4 rounds:
    # the ~5ms-step loop resolves a ~1% plane cost only if best-of gets
    # enough draws against this box's minute-scale throttle drift
    "step_stats": dict(cmd=[sys.executable,
                            os.path.join(REPO, "benchmarks",
                                         "telemetry_overhead.py"),
                            "--step-stats", "--rounds", "4"],
                       timeout=1200),
    # request tracing plane cost guard (docs/observability.md): paired
    # interleaved OFF/ON segments of the small-task loop at the DEFAULT
    # trace_sample_rate (telemetry + events pinned on); the
    # tracing_overhead row carries the same <=3% bar.  4 rounds -> 64
    # pairs: the task loop schedules a worker process per call, so
    # per-pair ratios spread +-15% on this box and the median needs
    # that many draws to resolve a ~1% plane cost
    "tracing": dict(cmd=[sys.executable,
                         os.path.join(REPO, "benchmarks",
                                      "telemetry_overhead.py"),
                         "--tracing", "--rounds", "4"],
                    timeout=1200),
    # metrics-history plane cost guard (docs/observability.md): paired
    # interleaved OFF/ON segments of metrics-shaped kv_put RPCs against
    # an in-process GcsServer at the default retention geometry
    # (telemetry + events pinned on); the history_overhead row carries
    # the same <=3% bar.  4 rounds -> 64 pairs, same reasoning as the
    # tracing arm: per-pair ratios on this box spread several percent
    # and the median needs the draws to resolve a ~1% plane cost
    "history": dict(cmd=[sys.executable,
                         os.path.join(REPO, "benchmarks",
                                      "telemetry_overhead.py"),
                         "--history", "--rounds", "4"],
                    timeout=1200),
    "serve_llm": dict(cmd=[sys.executable,
                           os.path.join(REPO, "benchmarks", "serve_llm.py"),
                           "--suite", "--slots", "32", "--requests", "128"],
                      timeout=5400),
    # disaggregated prefill/decode serving (docs/serve_disagg.md):
    # closed-loop interleaved A/B at 1k concurrent streaming
    # connections, colocated vs split pools at equal chip count —
    # the ab row carries the bars (ttft_p99_ratio >= 2,
    # tokens_per_s_ratio >= 0.9, handoff p50 < one decode block,
    # errors == 0)
    "serve_disagg": dict(cmd=[sys.executable,
                              os.path.join(REPO, "benchmarks",
                                           "serve_disagg.py"),
                              "--connections", "1000",
                              "--duration", "90",
                              "--new-tokens", "96"],
                         timeout=3600),
    # serving front door (docs/serve_frontdoor.md): closed-loop SSE
    # ingress + prefix-affinity routing + quantized handoffs under the
    # bimodal shared-prefix mix — the row carries the per-pool
    # TTFT/TPOT SLO classification from the trace plane, the prefix
    # hit rate (must be nonzero on this mix) and the bytes the int8
    # handoff codec kept off the wire
    "serve_frontdoor": dict(cmd=[sys.executable,
                                 os.path.join(REPO, "benchmarks",
                                              "serve_frontdoor.py"),
                                 "--connections", "1000",
                                 "--duration", "60",
                                 "--new-tokens", "32"],
                            timeout=3600),
    "rl": dict(cmd=[sys.executable,
                    os.path.join(REPO, "benchmarks", "rl_perf.py")],
               timeout=3600),   # PPO-to-150 + 2 IMPALA rows on 1 core
    # podracer RL data plane (docs/rl_podracer.md): IMPALA + PPO
    # env-frames/s A/B vs the blocking executor (same fleet, same
    # budget, mid-run actor-kill probe in the podracer arm) and the
    # fleet-floor weight-adoption latency at 2/4/8 actors — the
    # sub-linear growth bar for the store-routed multi-source broadcast
    "rl_podracer": dict(cmd=[sys.executable,
                             os.path.join(REPO, "benchmarks",
                                          "rl_podracer.py")],
                        timeout=2400),
    "vision": dict(cmd=[sys.executable,
                        os.path.join(REPO, "benchmarks", "vision_perf.py")],
                   timeout=1800),
    # the composed-kernel MFU ceiling for the flagship model (VERDICT r4
    # task #5's "committed roofline note"); ~20 min of chip compiles
    "roofline": dict(cmd=[sys.executable,
                          os.path.join(REPO, "benchmarks",
                                       "roofline_gpt.py")],
                     timeout=3600),
}


# Control-plane rows whose regressions the RPC fast path must keep
# visible (docs/rpc_fastpath.md): fresh core numbers are compared against
# the COMMITTED MICROBENCH.json (git HEAD), not the working copy, so a
# refresh that regressed the task path can't silently rebase its own
# baseline before the diff is reviewed.
_CONTROL_PLANE_ROWS = {
    "single client tasks sync": "tasks_sync_ops_s",
    "1:1 actor calls sync": "actor_sync_ops_s",
}

# Streaming-generator rows (docs/streaming_generators.md): the per-item
# report path's throughput must stay visible the same way.
_STREAMING_ROWS = {
    "streaming 100-yield": "streaming_items_s",
}

# Compiled-DAG rows (docs/compiled_dag.md): the channel hot loop's
# per-execute rate must stay visible the same way.
_COMPILED_DAG_ROWS = {
    "compiled_dag 3-stage": "compiled_dag_execs_s",
}

# Object-transfer rows (docs/object_transfer.md): the data plane's pull
# bandwidth must stay visible the same way (mb_per_s rows).
_OBJECT_TRANSFER_ROWS = {
    "pull 64MiB pipelined": "pull_pipelined_mb_s",
    "pull 64MiB striped 2-source busy hosts": "pull_striped_mb_s",
}

# Collective rows (docs/collective.md): the DCN data plane's allreduce /
# broadcast bandwidth must stay visible the same way.
_COLLECTIVE_ROWS = {
    "allreduce 64MiB ws4 new": "collective_allreduce_ws4_mb_s",
    "allreduce 64MiB ws2 new": "collective_allreduce_ws2_mb_s",
    "broadcast 64MiB ws4 new": "collective_broadcast_ws4_mb_s",
}

# Quantized-collective rows (docs/collective.md): the int8 wire-codec
# bandwidth and the async-overlap hidden fraction must stay visible the
# same way — the tracked field differs per row.
_COLLECTIVE_QUANT_ROWS = {
    "allreduce 64MiB ws4 sim-dcn int8": ("mb_per_s",
                                         "collective_quant_int8_mb_s"),
    "allreduce 64MiB ws4 sim-dcn fp32": ("mb_per_s",
                                         "collective_quant_fp32_mb_s"),
    "allreduce 8MiB ws4 overlap hidden-frac": (
        "hidden_frac", "collective_overlap_hidden_frac"),
}

# Disaggregated-serving rows (docs/serve_disagg.md): the A/B bars must
# stay visible the same way — rows are keyed by "metric" and the
# tracked value differs per row.
_SERVE_DISAGG_ROWS = {
    "serve_disagg_ab": ("ttft_p99_ratio", "disagg_ttft_p99_ratio"),
    "serve_disagg_disaggregated": ("tokens_per_s",
                                   "disagg_tokens_per_s"),
}


# Front-door rows (docs/serve_frontdoor.md): the closed-loop ingress
# row's throughput and prefix-affinity effectiveness must stay visible
# the same way.
_SERVE_FRONTDOOR_ROWS = {
    "serve_frontdoor_closed_loop": [
        ("tokens_per_s", "frontdoor_tokens_per_s"),
        ("prefix_hit_rate", "frontdoor_prefix_hit_rate"),
    ],
}


def serve_frontdoor_deltas(rows, committed):
    """Same contract as the other delta families for the front-door
    closed-loop row (two tracked fields per row)."""
    if not committed:
        return {}
    base = {}
    for r in committed.get("serve_frontdoor", []):
        if isinstance(r, dict) and r.get("metric") in _SERVE_FRONTDOOR_ROWS:
            for field, key in _SERVE_FRONTDOOR_ROWS[r["metric"]]:
                if r.get(field):
                    base[key] = r[field]
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        for field, key in _SERVE_FRONTDOOR_ROWS.get(row.get("metric"),
                                                    ()):
            if key not in base or not row.get(field):
                continue
            prev, cur = base[key], row[field]
            out[key] = {"committed": prev, "current": cur,
                        "ratio": round(cur / prev, 3)}
    return out


def serve_disagg_deltas(rows, committed):
    """Same contract as the other delta families for the serve_disagg
    section's bar rows."""
    if not committed:
        return {}
    base = {}
    for r in committed.get("serve_disagg", []):
        if isinstance(r, dict) and r.get("metric") in _SERVE_DISAGG_ROWS:
            field, key = _SERVE_DISAGG_ROWS[r["metric"]]
            if r.get(field):
                base[key] = (field, r[field])
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        spec = _SERVE_DISAGG_ROWS.get(row.get("metric"))
        if spec is None:
            continue
        field, key = spec
        if key not in base or not row.get(field):
            continue
        prev, cur = base[key][1], row[field]
        out[key] = {"committed": prev, "current": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def _committed_baseline(path):
    """Core rows of the committed MICROBENCH.json (None outside git)."""
    try:
        rel = os.path.relpath(path, REPO)
        blob = subprocess.run(
            ["git", "-C", REPO, "show", f"HEAD:{rel}"],
            capture_output=True, text=True, timeout=30)
        if blob.returncode != 0:
            return None
        return json.loads(blob.stdout)
    except (OSError, ValueError, subprocess.TimeoutExpired):
        return None


def control_plane_deltas(core_rows, committed):
    """{metric: {committed, current, ratio}} for the RPC-path rows."""
    if not committed:
        return {}
    base = {r["name"]: r.get("ops_per_s")
            for r in committed.get("core", []) if isinstance(r, dict)}
    out = {}
    for row in core_rows:
        key = _CONTROL_PLANE_ROWS.get(row.get("name"))
        if key is None or not base.get(row["name"]):
            continue
        prev, cur = base[row["name"]], row["ops_per_s"]
        out[key] = {"committed_ops_s": prev, "current_ops_s": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def streaming_deltas(stream_rows, committed):
    """Same contract for the streaming section's items/s rows."""
    if not committed:
        return {}
    base = {r["name"]: r.get("items_per_s")
            for r in committed.get("streaming", []) if isinstance(r, dict)}
    out = {}
    for row in stream_rows:
        if not isinstance(row, dict):
            continue
        key = _STREAMING_ROWS.get(row.get("name"))
        if key is None or not base.get(row["name"]) \
                or not row.get("items_per_s"):
            continue
        prev, cur = base[row["name"]], row["items_per_s"]
        out[key] = {"committed_items_s": prev, "current_items_s": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def compiled_dag_deltas(rows, committed):
    """Same contract for the compiled-DAG section's executes/s row."""
    if not committed:
        return {}
    base = {r["name"]: r.get("ops_per_s")
            for r in committed.get("compiled_dag", []) if isinstance(r, dict)}
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        key = _COMPILED_DAG_ROWS.get(row.get("name"))
        if key is None or not base.get(row["name"]) \
                or not row.get("ops_per_s"):
            continue
        prev, cur = base[row["name"]], row["ops_per_s"]
        out[key] = {"committed_execs_s": prev, "current_execs_s": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def object_transfer_deltas(rows, committed):
    """Same contract for the object-transfer section's bandwidth rows."""
    if not committed:
        return {}
    base = {r["name"]: r.get("mb_per_s")
            for r in committed.get("object_transfer", [])
            if isinstance(r, dict)}
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        key = _OBJECT_TRANSFER_ROWS.get(row.get("name"))
        if key is None or not base.get(row["name"]) \
                or not row.get("mb_per_s"):
            continue
        prev, cur = base[row["name"]], row["mb_per_s"]
        out[key] = {"committed_mb_s": prev, "current_mb_s": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def collective_deltas(rows, committed):
    """Same contract for the collective section's bandwidth rows."""
    if not committed:
        return {}
    base = {r["name"]: r.get("mb_per_s")
            for r in committed.get("collective", [])
            if isinstance(r, dict)}
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        key = _COLLECTIVE_ROWS.get(row.get("name"))
        if key is None or not base.get(row["name"]) \
                or not row.get("mb_per_s"):
            continue
        prev, cur = base[row["name"]], row["mb_per_s"]
        out[key] = {"committed_mb_s": prev, "current_mb_s": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def collective_quant_deltas(rows, committed):
    """Same contract for the collective_quant section; the tracked field
    differs per row (mb_per_s for the codec arms, hidden_frac for the
    overlap probe)."""
    if not committed:
        return {}
    base = {}
    for r in committed.get("collective_quant", []):
        if isinstance(r, dict) and r.get("name") in _COLLECTIVE_QUANT_ROWS:
            field, key = _COLLECTIVE_QUANT_ROWS[r["name"]]
            if r.get(field):
                base[key] = r[field]
    out = {}
    for row in rows:
        if not isinstance(row, dict):
            continue
        spec = _COLLECTIVE_QUANT_ROWS.get(row.get("name"))
        if spec is None:
            continue
        field, key = spec
        if key not in base or not row.get(field):
            continue
        prev, cur = base[key], row[field]
        out[key] = {"committed": prev, "current": cur,
                    "ratio": round(cur / prev, 3)}
    return out


def merge_preserve(out, prev, regenerated):
    """Carry over every section of `prev` that this run didn't regenerate.

    This is the fix for the round-4 data loss where a refresh that only
    ran {core,serve,data,serve_llm} rewrote the whole file and dropped
    the `rl` section: unknown or un-regenerated keys now survive.
    """
    meta = {"generated", "host", "note"}
    for key, val in prev.items():
        if key in meta or key in regenerated:
            continue
        out[key] = val
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output",
                    default=os.path.join(REPO, "MICROBENCH.json"))
    ap.add_argument("--only", nargs="*", default=None,
                    help="run only these sections (others are preserved "
                         "from the existing output file)")
    args = ap.parse_args()
    selected = list(SECTIONS) if args.only is None else args.only
    unknown = [s for s in selected if s not in SECTIONS]
    if unknown:
        ap.error(f"unknown sections {unknown}; known: {list(SECTIONS)}")

    try:
        import psutil
        mem_gb = round(psutil.virtual_memory().total / 1024**3, 1)
        cpus = psutil.cpu_count(logical=False) or os.cpu_count()
    except ImportError:
        mem_gb = None
        cpus = os.cpu_count()

    out = {
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "host": {"cpus": os.cpu_count(), "physical_cpus": cpus,
                 "memory_gb": mem_gb, "platform": platform.platform()},
        "note": "reference microbenchmark runs on 16+ core machines; this "
                "box is a 1-2 core heavily throttled VM whose absolute "
                "throughput drifts hour to hour — per-core comparisons "
                "only, and control-plane code comparisons should use "
                "interleaved same-box A/B ratios "
                "(control_plane_same_box_vs_seed), not cross-refresh "
                "absolute deltas",
    }

    regenerated = set()
    for name in selected:
        spec = SECTIONS[name]
        script = next((a for a in spec["cmd"] if a.endswith(".py")), None)
        if script and not os.path.exists(script):
            # tolerable on a default all-sections sweep (a section can be
            # registered ahead of its script landing), but an explicit
            # --only request for it is a user error
            if args.only is not None:
                ap.error(f"--only {name}: {script} does not exist")
            print(f"[collect] {name}: {script} missing, skipping "
                  "(existing numbers preserved)", flush=True)
            continue
        print(f"[collect] {name}: {' '.join(spec['cmd'][1:])}", flush=True)
        try:
            rows, rc = _run_json_lines(spec["cmd"], timeout=spec["timeout"])
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            # one failing section must not abort the sweep or discard the
            # sections that already completed
            print(f"[collect] {name}: FAILED ({e}); "
                  "keeping previous numbers", flush=True)
            continue
        if spec.get("last_list") and rows and isinstance(rows[-1], list):
            rows = rows[-1]
        if not rows or rc != 0:
            # no JSON output, or a crash after partial output: either way
            # the previous good numbers survive — a truncated row set
            # must never replace a complete one
            print(f"[collect] {name}: "
                  f"{'no JSON rows' if not rows else f'rc={rc} (partial)'}"
                  ", keeping previous numbers", flush=True)
            continue
        out[name] = rows
        regenerated.add(name)

    # merge-preserve: sections this run didn't regenerate (including the
    # envelope written by tests/test_scale_envelope.py, and any section a
    # future program adds) survive the refresh
    try:
        with open(args.output) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        prev = {}
    merge_preserve(out, prev, regenerated)

    committed = None
    if regenerated & {"core", "streaming", "compiled_dag",
                      "object_transfer", "collective",
                      "collective_quant", "serve_disagg",
                      "serve_frontdoor"}:
        committed = _committed_baseline(args.output)
    if "core" in regenerated:
        deltas = control_plane_deltas(out["core"], committed)
        if deltas:
            out["control_plane_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed_ops_s']:,.0f} -> "
                      f"{d['current_ops_s']:,.0f} ops/s "
                      f"(x{d['ratio']}) [{tag}]", flush=True)
    if "streaming" in regenerated:
        deltas = streaming_deltas(out["streaming"], committed)
        if deltas:
            out["streaming_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed_items_s']:,.0f} -> "
                      f"{d['current_items_s']:,.0f} items/s "
                      f"(x{d['ratio']}) [{tag}]", flush=True)
    if "compiled_dag" in regenerated:
        deltas = compiled_dag_deltas(out["compiled_dag"], committed)
        if deltas:
            out["compiled_dag_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed_execs_s']:,.0f} -> "
                      f"{d['current_execs_s']:,.0f} execs/s "
                      f"(x{d['ratio']}) [{tag}]", flush=True)
    if "object_transfer" in regenerated:
        deltas = object_transfer_deltas(out["object_transfer"], committed)
        if deltas:
            out["object_transfer_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed_mb_s']:,.0f} -> "
                      f"{d['current_mb_s']:,.0f} MB/s "
                      f"(x{d['ratio']}) [{tag}]", flush=True)
    if "collective" in regenerated:
        deltas = collective_deltas(out["collective"], committed)
        if deltas:
            out["collective_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed_mb_s']:,.0f} -> "
                      f"{d['current_mb_s']:,.0f} MB/s "
                      f"(x{d['ratio']}) [{tag}]", flush=True)
    if "collective_quant" in regenerated:
        deltas = collective_quant_deltas(out["collective_quant"], committed)
        if deltas:
            out["collective_quant_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed']:,.2f} -> "
                      f"{d['current']:,.2f} (x{d['ratio']}) [{tag}]",
                      flush=True)
    if "serve_disagg" in regenerated:
        deltas = serve_disagg_deltas(out["serve_disagg"], committed)
        if deltas:
            out["serve_disagg_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed']:,.2f} -> "
                      f"{d['current']:,.2f} (x{d['ratio']}) [{tag}]",
                      flush=True)
    if "serve_frontdoor" in regenerated:
        deltas = serve_frontdoor_deltas(out["serve_frontdoor"],
                                        committed)
        if deltas:
            out["serve_frontdoor_deltas"] = deltas
            for key, d in deltas.items():
                tag = "REGRESSION" if d["ratio"] < 0.9 else "ok"
                print(f"[collect] {key}: {d['committed']:,.2f} -> "
                      f"{d['current']:,.2f} (x{d['ratio']}) [{tag}]",
                      flush=True)

    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    print(f"[collect] wrote {args.output}")


if __name__ == "__main__":
    main()
