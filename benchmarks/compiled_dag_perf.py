"""Compiled DAG microbenchmark: per-execute latency vs the classic path.

Measures the capability the subsystem exists for (docs/compiled_dag.md):
a 3-stage small-payload actor chain executed through a ``CompiledDAG``
(preallocated shm channels + resident actor loops, ZERO per-call task
submission) against the equivalent classic ``dag.execute()`` on the
SAME live actors (per-call actor-task submission, lease/push RPCs and
driver-mediated ObjectRef resolution).  The two arms run **interleaved
in alternating rounds** on the same cluster so this box's VM-throttle
drift hits both equally; the medians of the per-round rates are
reported.

Prints JSON lines:
  {"name": "compiled_dag 3-stage", "per_execute_ms", "ops_per_s"}
  {"name": "classic dag 3-stage", "per_execute_ms", "ops_per_s"}
  {"name": "compiled vs classic per-execute", "speedup"}   # >=5x bar
  {"name": "compiled_dag shm growth 1k", "bytes_delta"}    # == 0 bar
"""

import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

ROUNDS = int(os.environ.get("COMPILED_DAG_BENCH_ROUNDS", "5"))
ITERS = int(os.environ.get("COMPILED_DAG_BENCH_ITERS", "100"))
LEAK_ITERS = 1000


def main():
    import ray_tpu
    from ray_tpu.dag import InputNode
    from ray_tpu.runtime.core_worker import get_global_worker

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        @ray_tpu.remote
        class Stage:
            def __init__(self, inc):
                self.inc = inc

            def add(self, x):
                return x + self.inc

        # one set of live actors serves BOTH arms: the comparison is
        # purely submission protocol, not actor placement
        stages = [Stage.remote(10 ** i) for i in range(3)]
        with InputNode() as inp:
            node = inp
            for h in stages:
                node = h.add.bind(node)
        classic = node

        # warm leases/pipes on the classic path
        for i in range(10):
            assert ray_tpu.get(classic.execute(i)) == i + 111

        cdag = classic.experimental_compile(max_inflight=2,
                                            name="bench-3stage")
        for i in range(20):
            assert cdag.execute(i).get(timeout=60) == i + 111

        classic_ms, compiled_ms = [], []
        for _round in range(ROUNDS):
            t0 = time.perf_counter()
            for i in range(ITERS):
                ray_tpu.get(classic.execute(i))
            classic_ms.append((time.perf_counter() - t0) / ITERS * 1e3)
            t0 = time.perf_counter()
            for i in range(ITERS):
                cdag.execute(i).get(timeout=60)
            compiled_ms.append((time.perf_counter() - t0) / ITERS * 1e3)

        cls_ms = statistics.median(classic_ms)
        cmp_ms = statistics.median(compiled_ms)
        print(json.dumps({
            "name": "compiled_dag 3-stage",
            "per_execute_ms": round(cmp_ms, 3),
            "ops_per_s": round(1000.0 / cmp_ms, 1),
        }), flush=True)
        print(json.dumps({
            "name": "classic dag 3-stage",
            "per_execute_ms": round(cls_ms, 3),
            "ops_per_s": round(1000.0 / cls_ms, 1),
        }), flush=True)
        print(json.dumps({
            "name": "compiled vs classic per-execute",
            "speedup": round(cls_ms / cmp_ms, 1),
        }), flush=True)

        # slot-reuse leak guard: 1k executes must leave shm flat
        store = get_global_worker().store
        before = store.stats()["bytes_in_use"]
        for i in range(LEAK_ITERS):
            cdag.execute(i).get(timeout=60)
        after = store.stats()["bytes_in_use"]
        print(json.dumps({
            "name": "compiled_dag shm growth 1k",
            "bytes_delta": int(after - before),
        }), flush=True)
        cdag.teardown()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
