"""One-variant MFU measurement for the gpt-large remat/chunk sweep.

Run one configuration per process (fresh HBM + compile cache):
  python benchmarks/mfu_sweep.py --policy block_outs --batch 8 --chunk 256
Prints one JSON line; the sweep results are recorded in bench.py's
comments and BENCH notes.
"""

import argparse
import functools
import json
import os
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt-large")
    ap.add_argument("--policy", default="nothing")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--attn", default="flash")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--remat-layers", type=int, default=None)
    args = ap.parse_args()

    import bench
    from ray_tpu.models import get_config
    from ray_tpu.train.step import OptimizerConfig, lm_loss_chunked_fn

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "")
    peak = next((v for k, v in bench.PEAK_FLOPS.items() if k in kind),
                197e12)

    # _bench_one re-imports lm_loss_chunked_fn at call time, so patching
    # the module attribute injects our chunk size
    loss_fn = functools.partial(lm_loss_chunked_fn, chunk_size=args.chunk)
    try:
        cfg = get_config(args.config, max_seq_len=args.seq, remat=True,
                         remat_policy=args.policy,
                         remat_layers=args.remat_layers,
                         attention_impl=args.attn)
        import ray_tpu.train.step as step_mod
        orig = step_mod.lm_loss_chunked_fn
        step_mod.lm_loss_chunked_fn = loss_fn
        try:
            res = bench._bench_one(
                cfg, args.batch, args.seq, steps=args.steps, warmup=3,
                peak=peak,
                optimizer=OptimizerConfig(warmup_steps=10, decay_steps=1000,
                                          optimizer="adafactor"),
                chunked=True)
        finally:
            step_mod.lm_loss_chunked_fn = orig
        res.update({"policy": args.policy, "batch": args.batch,
                    "chunk": args.chunk, "attn": args.attn,
                    "seq": args.seq, "ok": True})
    except Exception as e:
        res = {"policy": args.policy, "batch": args.batch,
               "chunk": args.chunk, "attn": args.attn, "seq": args.seq,
               "ok": False,
               "error": f"{type(e).__name__}: {str(e)[:200]}"}
    print(json.dumps(res))


if __name__ == "__main__":
    main()
