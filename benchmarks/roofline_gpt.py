"""Component roofline for the GPT train step on one chip.

VERDICT round-4 task #5: gpt-small MFU has been flat at ~54.6% across
rounds while the builder's sweeps exhausted every schedule knob.  This
measures WHERE the step time must go: each GEMM family in the model at
the bench shapes (fwd + dgrad + wgrad), the flash-attention kernel
fwd+bwd, and the norm/rope elementwise chains — then composes the best
MFU any schedule could reach given those measured kernel efficiencies.
If the composed ceiling matches the observed step MFU, the gap is MXU
shape efficiency at d_model-sized tiles, not missing fusion.

Measurement note: on the axon remote-chip transport a per-dispatch
timing loop measures round-trips, not kernels, and even a single fenced
call carries ~100s of ms of tunnel overhead.  Every probe therefore
compiles the same dependent chain at TWO iteration counts and reports
(T(N2) - T(N1)) / (N2 - N1): the dispatch, fence, and transfer overheads
are identical between the two and difference away, leaving pure device
time per iteration.

  python benchmarks/roofline_gpt.py [--preset gpt-small] [--batch 16]

Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

def _time_two_point(make_chain, x0, n1, n2, calls=3):
    """Seconds per chained iteration via the two-point difference (see
    module docstring).  `make_chain(iters)` returns a jitted fn(x).
    Callers size n2 so the differenced device work is >= ~1 s — the
    tunnel adds O(100 ms) call-to-call jitter that a small difference
    cannot survive; min-of-calls rejects the positive outliers."""
    times = {}
    for n in (n1, n2):
        fn = make_chain(n)
        for attempt in range(3):
            try:
                out = fn(x0)                      # compile + warm
                np.asarray(
                    jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
                break
            except Exception:                     # transient tunnel hiccup
                if attempt == 2:
                    raise
                time.sleep(5)
        best = float("inf")
        for _ in range(calls):
            t0 = time.perf_counter()
            out = fn(x0)
            np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]
            best = min(best, time.perf_counter() - t0)
        times[n] = best
    return max((times[n2] - times[n1]) / (n2 - n1), 1e-9)


def time_gemm_pair(m, k, n):
    """One chained iteration = GEMM [m,k]x[k,n] + GEMM [m,n]x[n,k]
    (exactly a fwd + dgrad pair).  Returns seconds per PAIR."""
    rng = np.random.default_rng(0)
    b = jnp.asarray(rng.standard_normal((k, n)), jnp.bfloat16)
    b2 = jnp.asarray(rng.standard_normal((n, k)), jnp.bfloat16)
    x0 = jnp.asarray(rng.standard_normal((m, k)), jnp.bfloat16)

    def make_chain(iters):
        @jax.jit
        def chain(x):
            def body(i, x):
                y = jax.lax.dot(x, b)              # [m, n]
                # the relu breaks dot reassociation — without it XLA
                # rewrites dot(dot(x,b),b2) as dot(x, hoisted b@b2) and
                # the probe times ONE matmul while crediting two (first
                # run measured 239 "TFLOPs" on a 197-peak chip); it
                # fuses into the matmul epilogue, costing nothing
                y = jnp.maximum(y, 0) * jnp.bfloat16(3e-2)
                return jax.lax.dot(y, b2)
            out = jax.lax.fori_loop(0, iters, body, x)
            # scalar output: the fence must not fetch a 100 MB array
            # over the tunnel (3+ s of jittery transfer per call)
            return out[0, 0].astype(jnp.float32)
        return chain

    # ~0.5 ms/pair at the small shapes: 2048 extra iters ~ 1-4 s
    return _time_two_point(make_chain, x0, 8, 2056)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt-small")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args()

    from ray_tpu.models.configs import get_config

    cfg = get_config(args.preset, max_seq_len=args.seq)
    B, S, D, F, V = (args.batch, args.seq, cfg.d_model, cfg.d_ff,
                     cfg.vocab_size)
    M = B * S
    dev = jax.devices()[0]
    peak = 197e12
    L = cfg.n_layers

    # GEMM families.  A pair probe (fwd+dgrad shapes) and a transposed
    # pair for the wgrad character; per-family fwd+bwd cost = 1.5 pairs.
    fams = {
        "qkv_o": ((M, D, D), 4 * L),
        "mlp_up": ((M, D, F), 2 * L),
        "mlp_down": ((M, F, D), 1 * L),
        "lm_head": ((M, D, V), 1),
    }
    rows = {}
    total_t = 0.0
    total_fl = 0.0
    for name, ((m, k, n), count) in fams.items():
        dt_pair = time_gemm_pair(m, k, n)
        dt_wg = time_gemm_pair(k, m, n) if name != "lm_head" else dt_pair
        pair_fl = 2 * 2 * m * k * n
        # fwd + dgrad from the pair, wgrad as half the transposed pair
        fam_t = dt_pair + dt_wg / 2
        fam_fl = 3 * 2 * m * k * n
        rows[name] = {
            "shape": [m, k, n],
            "pair_tflops": round(pair_fl / dt_pair / 1e12, 1),
            "fwd_bwd_efficiency": round(fam_fl / (fam_t * peak), 3)}
        total_t += count * fam_t
        total_fl += count * fam_fl

    # flash attention fwd+bwd at the model's shapes (chained via q)
    from ray_tpu.ops.attention import attention
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.standard_normal(
        (B, S, cfg.n_heads, cfg.head_dim)), jnp.bfloat16)
    k0, v0 = q0 + 0, q0 * jnp.bfloat16(0.5)

    def attn_loss(q, k, v):
        return attention(q, k, v, causal=True, impl="flash").astype(
            jnp.float32).sum()

    grad = jax.grad(attn_loss, argnums=(0, 1, 2))

    def make_attn(iters):
        @jax.jit
        def chain(q):
            def body(i, q):
                dq, _, _ = grad(q, k0, v0)
                return (q - dq * jnp.bfloat16(1e-3)).astype(jnp.bfloat16)
            out = jax.lax.fori_loop(0, iters, body, q)
            return out[0, 0, 0, 0].astype(jnp.float32)
        return chain

    dt_attn = _time_two_point(make_attn, q0, 8, 136)
    # causal ~0.5x of full; fwd(1x) + bwd(2.5x) of the fwd flops
    attn_fl = 4 * B * cfg.n_heads * S * S * cfg.head_dim * 0.5 * 3.5
    rows["flash_attn_fwd_bwd"] = {
        "ms": round(dt_attn * 1e3, 2),
        "tflops": round(attn_fl / dt_attn / 1e12, 1),
        "efficiency": round(attn_fl / dt_attn / peak, 3)}
    total_t += L * dt_attn
    total_fl += L * attn_fl

    # norm + rope elementwise chains as XLA actually compiles them
    from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies
    x0 = jnp.asarray(rng.standard_normal((B, S, D)), jnp.bfloat16)
    w = jnp.ones((D,), jnp.float32)
    cos, sin = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)

    def make_norm(iters):
        return jax.jit(lambda x: jax.lax.fori_loop(
            0, iters, lambda i, x: rms_norm(x, w), x
            )[0, 0, 0].astype(jnp.float32))

    def make_rope(iters):
        return jax.jit(lambda q: jax.lax.fori_loop(
            0, iters, lambda i, q: apply_rope(q, cos, sin), q
            )[0, 0, 0, 0].astype(jnp.float32))

    dt_norm = _time_two_point(make_norm, x0, 8, 8200)
    dt_rope = _time_two_point(make_rope, q0, 8, 8200)
    rows["rms_norm"] = {"us": round(dt_norm * 1e6, 1),
                        "gbps": round(2 * x0.nbytes / dt_norm / 1e9, 1)}
    rows["rope"] = {"us": round(dt_rope * 1e6, 1),
                    "gbps": round(2 * q0.nbytes / dt_rope / 1e9, 1)}
    # per step: 2 norms + 2 ropes per layer + final norm; bwd ~2x traffic
    ew_t = L * (2 * dt_norm + 2 * dt_rope) * 3 + dt_norm * 3
    total_t += ew_t

    composed_mfu = total_fl / (total_t * peak)
    out = {
        "metric": "gpt_roofline",
        "preset": args.preset,
        "batch": B, "seq": S,
        "device": getattr(dev, "device_kind", dev.platform),
        "components": rows,
        "elementwise_share_pct": round(100 * ew_t / total_t, 1),
        "composed_kernel_time_ms": round(total_t * 1e3, 1),
        "composed_mfu_ceiling": round(composed_mfu, 4),
        "note": "ceiling composes MEASURED per-kernel efficiencies at "
                "the model's exact shapes with zero overhead between "
                "them; the bench.py step MFU can only approach this",
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
