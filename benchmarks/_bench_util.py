"""Helpers shared by the benchmark drivers."""


def percentiles(lat_s):
    """(p50_ms, p99_ms) of a list of latencies in seconds."""
    lat = sorted(lat_s)

    def pct(p):
        return lat[min(len(lat) - 1, int(p / 100 * len(lat)))] * 1000.0

    return pct(50), pct(99)
