"""Podracer RL data-plane benchmarks (docs/rl_podracer.md).

Three probes, one JSON line per row:

  * ``rl_podracer_{impala,ppo}`` — end-to-end env-frames/s A/B: the
    classic blocking executor (driver-submitted sample tasks, per-batch
    weight pushes) vs the podracer plane (streaming fragment ingestion +
    compiled-DAG learner + store-routed weight broadcast), same fleet
    shape, same per-arm iteration budget, run back to back on the same
    box.  The podracer arm also reports streaming time-to-first-fragment
    and the mid-run preemption probe: one rollout actor is killed halfway
    through and the row carries the max inter-iteration gap around the
    kill — the learner must not stall beyond one backpressure window.
  * ``rl_podracer_weight_sync`` — fleet-floor weight adoption latency at
    actor counts {2, 4, 8}: the learner put()s each version ONCE and the
    fleet pulls it striped from the store (every completed puller becomes
    a source), so the latency growth with fleet size must be sub-linear —
    8 actors must cost well under 4x the 2-actor floor.

  python benchmarks/rl_podracer.py [--iters 6] [--sizes 2 4 8]

Prints one JSON line per row.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _build(algo_name, podracer, num_workers=2, seed=0):
    """Same fleet and fragment shape in both arms.  Fragments are SHORT
    (10 steps) on purpose: that is the per-fragment-overhead regime the
    podracer plane targets — the classic executor pays one task
    submission + one full weight push per fragment, the podracer pays
    neither — while long fragments on a 1-core box leave both arms
    env-step-bound and the data plane invisible."""
    from ray_tpu.rl.impala import ImpalaConfig
    from ray_tpu.rl.ppo import PPOConfig
    if algo_name == "impala":
        cfg = (ImpalaConfig().environment("CartPole-v1")
               .rollouts(num_rollout_workers=num_workers,
                         rollout_fragment_length=10)
               .training(batches_per_step=16))
    else:
        # fragment length matches the learner-step granularity: the
        # podracer runs one compiled step per fragment, so frag 40 /
        # batch 160 gives both arms 4 learner updates per iteration —
        # the A/B then isolates the data plane (4 task submissions + a
        # weight broadcast per iter vs zero) instead of comparing
        # different SGD schedules
        cfg = (PPOConfig().environment("CartPole-v1")
               .rollouts(num_rollout_workers=num_workers,
                         rollout_fragment_length=40)
               .training(train_batch_size=160, sgd_minibatch_size=80,
                         num_sgd_iter=2))
    cfg = cfg.debugging(seed=seed)
    if podracer:
        cfg = cfg.podracer()
    return cfg.build()


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


def run_ab(algo_name, iters, windows=3, kill_mid_run=True):
    """Interleaved-window A/B (the collective_perf idiom): both arms are
    built once, then measured in alternating timed windows (classic,
    podracer, classic, ...) so CPU-frequency / scheduler drift on this
    1-core box hits both arms equally — back-to-back sequential arms
    flip verdicts run to run on noise alone.  The paused podracer fleet
    quiesces by construction (actors block on stream backpressure once
    the prefetch window fills), and each podracer window starts with one
    UNTIMED drain iteration so the backlog queued while the classic arm
    ran doesn't count as free frames.  Returns the A/B row."""
    import ray_tpu

    row = {"metric": f"rl_podracer_{algo_name}", "train_iters": iters,
           "ab_windows": windows, "num_rollout_workers": 2}

    base = _build(algo_name, podracer=False)
    t_build0 = time.monotonic()
    pod = _build(algo_name, podracer=True, seed=1)
    ex = pod.podracer
    try:
        # warm both: jit + worker pools + DAG compile; the podracer's
        # first train() is also the streaming time-to-first-iteration
        pod.train()
        row["time_to_first_iter_s"] = round(
            time.monotonic() - t_build0, 2)
        base.train()

        base_fps, pod_fps = [], []
        gaps = []
        for _ in range(windows):
            ts0 = base._timesteps_total
            t0 = time.monotonic()
            for _ in range(iters):
                base.train()
            base_fps.append((base._timesteps_total - ts0)
                            / (time.monotonic() - t0))

            pod.train()                    # untimed: drain the backlog
            r = pod.train()
            ts0 = r["timesteps_total"]
            t0 = time.monotonic()
            for _ in range(iters):
                it0 = time.monotonic()
                r = pod.train()
                gaps.append(time.monotonic() - it0)
            pod_fps.append((r["timesteps_total"] - ts0)
                           / (time.monotonic() - t0))

        row["baseline_frames_per_s"] = round(_median(base_fps), 1)
        row["podracer_frames_per_s"] = round(_median(pod_fps), 1)
        row["baseline_window_fps"] = [round(f, 1) for f in base_fps]
        row["podracer_window_fps"] = [round(f, 1) for f in pod_fps]
        row["speedup"] = round(row["podracer_frames_per_s"]
                               / max(row["baseline_frames_per_s"], 1e-9), 2)
        row["classic_submits_steady"] = ex.telemetry[
            "classic_submits_steady"]
        row["learner_steps"] = ex.telemetry["learner_steps"]
        row["steady_median_iter_gap_s"] = round(_median(gaps), 2)
        if ex.telemetry["weight_adoption_s"]:
            row["weight_adoption_p50_s"] = round(
                _median(ex.telemetry["weight_adoption_s"]), 3)

        if kill_mid_run:
            # the preemption probe, after the timed windows: kill one
            # rollout actor mid-run — the learner keeps stepping off the
            # surviving streams, and no iteration may stall beyond ~one
            # backpressure window of fragments
            ray_tpu.kill(ex._slots[0]["actor"])
            kgaps = []
            for _ in range(iters):
                it0 = time.monotonic()
                pod.train()
                kgaps.append(time.monotonic() - it0)
            row["preempt_max_iter_gap_s"] = round(max(kgaps), 2)
            row["preempt_median_iter_gap_s"] = round(_median(kgaps), 2)
            # train until the replacement rendezvous lands, then cite
            # the auditor's episode — the mid-run preemption row the
            # recovery table cross-checks
            deadline = time.monotonic() + 120
            while (ex.telemetry["replacements"] < 1
                   and time.monotonic() < deadline):
                pod.train()
            row["replacements"] = ex.telemetry["replacements"]
            from ray_tpu.runtime.core_worker import get_global_worker
            gcs = get_global_worker().gcs
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                eps = [e for e in (gcs.call(
                           "list_recovery_episodes",
                           {"kind": "rl_actor", "include_open": False})
                           or [])
                       if e.get("key", "").startswith(ex.run_id)]
                if eps:
                    row["rejoin_episode_latency_s"] = round(
                        eps[-1]["latency_s"], 2)
                    row["rejoin_weight_version"] = eps[-1].get(
                        "weight_version")
                    break
                time.sleep(0.3)
    finally:
        pod.stop()
        base.stop()
    return row


def run_weight_sync(sizes, payload_mb=8.0, rounds=9):
    """Fleet-floor adoption latency by fleet size: one publish, N actors
    pull striped from the store; the fleet's slowest pull closes the
    round.  Sub-linear growth with N is the multi-source striping bar.
    The sub-linearity verdict rides the PER-ACTOR pull p50: flat
    per-pull cost as the fleet grows is the striping claim (every
    completed puller is a source, so no source serializes the fleet).
    The fleet-floor wall (min over rounds — p50 at millisecond scale is
    scheduler-noise-dominated on a 1-core box) is reported as context
    but measures the driver-side fan-out of N classic pull RPCs, a
    harness artifact the real executor doesn't have (podracer actors
    poll autonomously; fleet-wide adoption latency in the A/B rows is
    the end-to-end number)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.rl.podracer.weights import WeightFollower, WeightPublisher

    @ray_tpu.remote(num_cpus=0.5)
    class Puller:
        def __init__(self, name):
            self._f = WeightFollower(name)

        def pull(self):
            out = self._f.poll()
            return None if out is None else (out[1],
                                             self._f.last_pull_ms)

    nelem = int(payload_mb * 1024 * 1024 / 4)
    params = {"w": np.arange(nelem, dtype=np.float32)}
    per_size = {}
    pull_p50 = {}
    for n in sizes:
        pub = WeightPublisher(f"bench-sync-{n}")
        pullers = [Puller.remote(f"bench-sync-{n}") for _ in range(n)]
        ray_tpu.get([p.pull.remote() for p in pullers], timeout=120)
        floors = []
        pull_ms = []
        for _ in range(rounds):
            pub.publish(params)
            t0 = time.monotonic()
            outs = ray_tpu.get([p.pull.remote() for p in pullers],
                               timeout=120)
            floors.append(time.monotonic() - t0)
            assert all(o is not None for o in outs)
            pull_ms.extend(o[1] for o in outs)
        pub.clear()
        for p in pullers:
            ray_tpu.kill(p)
        per_size[n] = round(min(floors), 3)
        pull_ms.sort()
        pull_p50[n] = round(pull_ms[len(pull_ms) // 2], 2)
    smallest, largest = sizes[0], sizes[-1]
    return {"metric": "rl_podracer_weight_sync",
            "payload_mb": payload_mb, "rounds": rounds,
            "fleet_floor_min_s_by_actors":
                {str(k): v for k, v in per_size.items()},
            "per_actor_pull_p50_ms_by_actors":
                {str(k): v for k, v in pull_p50.items()},
            "pull_growth_x_2_to_8": round(
                pull_p50[largest] / max(pull_p50[smallest], 1e-9), 2),
            "actors_growth_x": largest / smallest,
            "sublinear": pull_p50[largest]
                < (largest / smallest) * pull_p50[smallest]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--sizes", type=int, nargs="*", default=[2, 4, 8])
    ap.add_argument("--payload-mb", type=float, default=8.0)
    args = ap.parse_args()

    import ray_tpu
    ray_tpu.init(num_cpus=16, object_store_memory=512 * 1024 * 1024)
    try:
        # latency-sensitive probe first, on the fresh cluster: after the
        # A/B probes (hundreds of published weight versions, killed +
        # replaced actors) the 8-puller round reads 1000x slower
        print(json.dumps(run_weight_sync(sorted(args.sizes),
                                         args.payload_mb)), flush=True)
        print(json.dumps(run_ab("impala", args.iters)), flush=True)
        print(json.dumps(run_ab("ppo", args.iters)), flush=True)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
