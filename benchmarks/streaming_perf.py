"""Streaming ObjectRef generator microbenchmark.

Measures the capability this subsystem exists for
(docs/streaming_generators.md): with ``num_returns="streaming"`` a
100-yield generator task's FIRST item reaches the consumer while the
task is still running, where ``num_returns="dynamic"`` (and plain
multi-return) only surfaces refs at task completion.  Each yield
carries a small simulated production cost so the gap is the protocol's,
not the scheduler's.

Prints JSON lines:
  {"name": "streaming 100-yield", "items_per_s", "time_to_first_item_s",
   "total_s"}
  {"name": "dynamic 100-yield", "time_to_first_item_s", "total_s"}
  {"name": "streaming vs dynamic ttfi", "speedup"}

The ttfi speedup row is the acceptance bar (>= 5x earlier first item);
items_per_s is the per-item report-path throughput row tracked in
MICROBENCH.json streaming deltas.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_ITEMS = 100
ITEM_WORK_S = 0.002     # simulated per-item production cost


def _generator_task(n, work_s):
    for i in range(n):
        time.sleep(work_s)
        yield i


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)
    try:
        fn = ray_tpu.remote(num_cpus=1)(_generator_task)

        # warm the lease/worker so both modes measure the protocol, not
        # worker spawn
        list(ray_tpu.get(
            fn.options(num_returns="dynamic").remote(1, 0.0)))

        # ---- streaming: per-yield delivery
        t0 = time.monotonic()
        gen = fn.options(num_returns="streaming").remote(
            N_ITEMS, ITEM_WORK_S)
        first = next(gen)
        ttfi_s = time.monotonic() - t0
        ray_tpu.get(first)
        for ref in gen:
            ray_tpu.get(ref)
        total_s = time.monotonic() - t0
        print(json.dumps({
            "name": "streaming 100-yield",
            "items_per_s": round(N_ITEMS / total_s, 1),
            "time_to_first_item_s": round(ttfi_s, 4),
            "total_s": round(total_s, 4),
        }), flush=True)

        # ---- dynamic: refs appear only at task completion, so the
        # first item is observable no earlier than the whole task
        t0 = time.monotonic()
        gen_ref = fn.options(num_returns="dynamic").remote(
            N_ITEMS, ITEM_WORK_S)
        refs = list(ray_tpu.get(gen_ref))
        dyn_ttfi_s = time.monotonic() - t0
        ray_tpu.get(refs[0])
        for ref in refs:
            ray_tpu.get(ref)
        dyn_total_s = time.monotonic() - t0
        print(json.dumps({
            "name": "dynamic 100-yield",
            "items_per_s": round(N_ITEMS / dyn_total_s, 1),
            "time_to_first_item_s": round(dyn_ttfi_s, 4),
            "total_s": round(dyn_total_s, 4),
        }), flush=True)

        print(json.dumps({
            "name": "streaming vs dynamic ttfi",
            "speedup": round(dyn_ttfi_s / max(ttfi_s, 1e-9), 1),
        }), flush=True)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
