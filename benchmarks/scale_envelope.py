"""Single-node scalability envelope — the stretch dimensions.

Counterpart of the reference's single-node benchmark rows
(release/benchmarks/README.md:27-31: many-args, many-returns, queued
1M tasks, large objects on one node).  Sized for this box but the same
structures: the owner table and per-key lease queue at 1M submissions
(laddered — the reference drains the same way), argument staging at
10k refs into one task, 1k return slots from one task, and a multi-GiB
single object through the shm store.

Writes the ``envelope`` section of MICROBENCH.json:
    python benchmarks/scale_envelope.py [-o MICROBENCH.json]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def bench_1m_queued_tasks(n=1_000_000, wave=25_000):
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote(num_cpus=1)
    def echo(i):
        return i

    t0 = time.monotonic()
    done = 0
    ok = True
    while done < n:
        k = min(wave, n - done)
        refs = [echo.remote(done + j) for j in range(k)]
        vals = ray_tpu.get(refs, timeout=1800)
        ok = ok and vals == list(range(done, done + k))
        done += k
        print(f"  [1m-tasks] {done}/{n}", flush=True)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "queued_tasks_1m", "count": n,
            "seconds": round(dt, 1), "tasks_per_s": round(n / dt, 1),
            "pass": ok, "reference": "1M queued tasks on one node "
            "(release/benchmarks/README.md:31)"}


def bench_10k_args():
    import ray_tpu
    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote
    def consume(*args):
        return len(args), sum(args)

    n = 10_000
    t0 = time.monotonic()
    refs = [ray_tpu.put(i) for i in range(n)]
    count, total = ray_tpu.get(consume.remote(*refs), timeout=1800)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "args_10k_single_task", "count": n,
            "seconds": round(dt, 1),
            "pass": count == n and total == n * (n - 1) // 2,
            "reference": "10k object args to a single task "
            "(release/benchmarks/README.md:27)"}


def bench_1k_returns():
    import ray_tpu
    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)

    n = 1_000

    @ray_tpu.remote(num_returns=n)
    def produce():
        return tuple(range(n))

    t0 = time.monotonic()
    refs = produce.remote()
    vals = ray_tpu.get(list(refs), timeout=1800)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "returns_1k_single_task", "count": n,
            "seconds": round(dt, 1), "pass": vals == list(range(n)),
            "reference": "1k+ returns from a single task "
            "(release/benchmarks/README.md:28, 3k on 64-core)"}


def bench_multi_gib_object(gib=2):
    import numpy as np

    import ray_tpu
    size = gib * (1 << 30)
    ray_tpu.init(num_cpus=2,
                 object_store_memory=size + (1 << 30))
    t0 = time.monotonic()
    arr = np.arange(size // 8, dtype=np.int64)
    ref = ray_tpu.put(arr)
    put_s = time.monotonic() - t0
    t0 = time.monotonic()
    back = ray_tpu.get(ref, timeout=600)
    get_s = time.monotonic() - t0
    ok = back.shape == arr.shape and back[0] == 0 \
        and int(back[-1]) == size // 8 - 1 \
        and int(back[size // 16]) == size // 16
    del arr, back, ref
    ray_tpu.shutdown()
    return {"name": "single_object_gib", "gib": gib,
            "put_s": round(put_s, 2), "get_s": round(get_s, 2),
            "pass": bool(ok),
            "reference": "100 GiB objects on a 576 GB-RAM node "
            "(release/benchmarks/README.md:30); scaled to this box"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output",
                    default=os.path.join(REPO, "MICROBENCH.json"))
    ap.add_argument("--tasks", type=int, default=1_000_000)
    args = ap.parse_args()

    rows = []
    for fn in (lambda: bench_1m_queued_tasks(args.tasks),
               bench_10k_args, bench_1k_returns, bench_multi_gib_object):
        print(f"[envelope] {fn}", flush=True)
        rows.append(fn())
        print(json.dumps(rows[-1]), flush=True)

    try:
        with open(args.output) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    env = doc.setdefault("envelope", {})
    env["stretch"] = rows
    env["source"] = ("tests/test_scale_envelope.py (CI counts) + "
                     "benchmarks/scale_envelope.py (stretch)")
    with open(args.output, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"[envelope] wrote {args.output}")


if __name__ == "__main__":
    main()
