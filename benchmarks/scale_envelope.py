"""Single-node scalability envelope — the stretch dimensions.

Counterpart of the reference's single-node benchmark rows
(release/benchmarks/README.md:27-31: many-args, many-returns, queued
1M tasks, large objects on one node).  Sized for this box but the same
structures: the owner table and per-key lease queue at 1M submissions
(laddered — the reference drains the same way), argument staging at
10k refs into one task, 1k return slots from one task, and a multi-GiB
single object through the shm store.

Writes the ``envelope`` section of MICROBENCH.json:
    python benchmarks/scale_envelope.py [-o MICROBENCH.json]
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the envelope saturates this box for minutes at a time: give every
# daemon scaled liveness patience (see config.timeout_scale) so the GCS
# doesn't declare the loaded node dead mid-bench
os.environ.setdefault("RAY_TPU_TIMEOUT_SCALE", "8.0")


def bench_1m_queued_tasks(n=1_000_000, wave=25_000):
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote(num_cpus=1)
    def echo(i):
        return i

    t0 = time.monotonic()
    done = 0
    ok = True
    while done < n:
        k = min(wave, n - done)
        refs = [echo.remote(done + j) for j in range(k)]
        vals = ray_tpu.get(refs, timeout=1800)
        ok = ok and vals == list(range(done, done + k))
        done += k
        print(f"  [1m-tasks] {done}/{n}", flush=True)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "queued_tasks_1m", "count": n,
            "seconds": round(dt, 1), "tasks_per_s": round(n / dt, 1),
            "pass": ok, "reference": "1M queued tasks on one node "
            "(release/benchmarks/README.md:31)"}


def bench_10k_args():
    import ray_tpu
    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)

    @ray_tpu.remote
    def consume(*args):
        return len(args), sum(args)

    n = 10_000
    t0 = time.monotonic()
    refs = [ray_tpu.put(i) for i in range(n)]
    count, total = ray_tpu.get(consume.remote(*refs), timeout=1800)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "args_10k_single_task", "count": n,
            "seconds": round(dt, 1),
            "pass": count == n and total == n * (n - 1) // 2,
            "reference": "10k object args to a single task "
            "(release/benchmarks/README.md:27)"}


def bench_1k_returns():
    import ray_tpu
    ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)

    n = 1_000

    @ray_tpu.remote(num_returns=n)
    def produce():
        return tuple(range(n))

    t0 = time.monotonic()
    refs = produce.remote()
    vals = ray_tpu.get(list(refs), timeout=1800)
    dt = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "returns_1k_single_task", "count": n,
            "seconds": round(dt, 1), "pass": vals == list(range(n)),
            "reference": "1k+ returns from a single task "
            "(release/benchmarks/README.md:28, 3k on 64-core)"}


def bench_multi_gib_object(gib=20):
    """Cold AND warm put of one multi-GiB numpy object.

    The two rows separate what they measure: cold is first-touch page
    faults on a fresh tmpfs segment (paging-bound on VMs with on-demand
    memory — nothing userspace can speed up, which is why the raylet
    prefaults segments in the background); warm reuses the freed extent
    — the steady-state throughput a long-lived cluster sees, and the
    number comparable to the reference's plasma memcpy path."""
    import gc

    import numpy as np

    import ray_tpu

    # fit the box: segment + source array + headroom, all resident.
    # Unknown free memory -> conservative, not maximal.
    try:
        free = os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError):
        free = 0
    if not free:
        gib = min(gib, 2)
    while gib > 2 and (2 * gib + 6) * (1 << 30) > free:
        gib //= 2
    size = gib * (1 << 30)
    # prefault off: the cold row must measure a genuinely cold segment.
    # spill threshold 1.0: a 95%-full store is the POINT here, not a
    # pressure signal to spill the primary to disk mid-measurement.
    ray_tpu.init(num_cpus=2, object_store_memory=size + (1 << 30),
                 system_config={"object_store_prefault": False,
                                "object_spill_threshold": 1.0})
    from ray_tpu.runtime.core_worker import get_global_worker
    raylet = get_global_worker()._raylet
    n_elem = size // 8
    arr = np.arange(n_elem, dtype=np.int64)   # position-sensitive payload
    t0 = time.monotonic()
    ref = ray_tpu.put(arr)
    cold_put_s = time.monotonic() - t0
    t0 = time.monotonic()
    back = ray_tpu.get(ref, timeout=600)
    get_s = time.monotonic() - t0
    ok = back.shape == arr.shape and int(back[0]) == 0 \
        and int(back[-1]) == n_elem - 1 \
        and int(back[n_elem // 2]) == n_elem // 2
    del back, ref
    gc.collect()
    # wait for the free so the warm put reuses the (now faulted) extent
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline:
        st = raylet.call("store_stats", {}, timeout=10)
        if st["bytes_in_use"] < (1 << 26):
            break
        time.sleep(0.2)
    t0 = time.monotonic()
    ref2 = ray_tpu.put(arr)
    warm_put_s = time.monotonic() - t0
    back2 = ray_tpu.get(ref2, timeout=600)
    ok = ok and int(back2[-1]) == n_elem - 1 \
        and int(back2[n_elem // 3]) == n_elem // 3
    del arr, back2, ref2
    ray_tpu.shutdown()
    return {"name": "single_object_gib", "gib": gib,
            "cold_put_s": round(cold_put_s, 2),
            "warm_put_s": round(warm_put_s, 2),
            "warm_gib_per_s": round(gib / warm_put_s, 2),
            "get_s": round(get_s, 2),
            "pass": bool(ok),
            "reference": "100 GiB objects on a 576 GB-RAM node "
            "(release/benchmarks/README.md:30); scaled to this box. "
            "cold = VM first-touch paging floor; warm = steady state"}


def bench_10k_object_batched_get(n=10_000, payload=1024):
    """10k store objects fetched in ONE ray_tpu.get call."""
    import ray_tpu
    ray_tpu.init(num_cpus=2, object_store_memory=512 * 1024 * 1024)
    blob = b"x" * payload
    t0 = time.monotonic()
    refs = [ray_tpu.put((i, blob)) for i in range(n)]
    put_s = time.monotonic() - t0
    t0 = time.monotonic()
    vals = ray_tpu.get(refs, timeout=1800)
    get_s = time.monotonic() - t0
    ok = all(v[0] == i and len(v[1]) == payload
             for i, v in enumerate(vals))
    ray_tpu.shutdown()
    return {"name": "batched_get_10k_objects", "count": n,
            "payload_bytes": payload,
            "put_s": round(put_s, 2), "get_s": round(get_s, 2),
            "gets_per_s": round(n / get_s, 1), "pass": bool(ok),
            "reference": "many-object get on one node "
            "(release/benchmarks/README.md:27-29)"}


def bench_1k_actors(n=1_000):
    """1k live actors: create all, one round-trip call to each, kill."""
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024,
                 system_config={"worker_start_timeout_s": 300.0,
                                "actor_creation_timeout_s": 300.0})

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    t0 = time.monotonic()
    actors = []
    wave = 50
    for s in range(0, n, wave):
        batch = [A.remote(i) for i in range(s, min(s + wave, n))]
        # laddered like the reference's many_actors release test: ready-
        # wait each wave so spawn bursts don't trip start timeouts
        ray_tpu.get([a.who.remote() for a in batch], timeout=1800)
        actors += batch
        print(f"  [1k-actors] {len(actors)}/{n}", flush=True)
    create_s = time.monotonic() - t0
    t0 = time.monotonic()
    vals = ray_tpu.get([a.who.remote() for a in actors], timeout=1800)
    call_s = time.monotonic() - t0
    ok = vals == list(range(n))
    t0 = time.monotonic()
    for a in actors:
        ray_tpu.kill(a)
    kill_s = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "actors_1k_registered_responding", "count": n,
            "create_s": round(create_s, 1), "call_all_s": round(call_s, 1),
            "kill_s": round(kill_s, 1), "pass": bool(ok),
            "reference": "10k actors across 64 nodes "
            "(release/benchmarks/README.md:10); one node here"}


def bench_500_pgs(n=500):
    """500 placement groups created, ready-waited, and removed."""
    import ray_tpu
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    t0 = time.monotonic()
    pgs = []
    ok = True
    for i in range(n):
        pg = placement_group([{"CPU": 0.001}])
        ok = ok and pg.wait(timeout_seconds=60)
        pgs.append(pg)
    create_s = time.monotonic() - t0
    t0 = time.monotonic()
    for pg in pgs:
        remove_placement_group(pg)
    remove_s = time.monotonic() - t0
    ray_tpu.shutdown()
    return {"name": "placement_groups_500", "count": n,
            "create_ready_s": round(create_s, 1),
            "remove_s": round(remove_s, 1), "pass": bool(ok),
            "reference": "1k placement groups across 64 nodes "
            "(release/benchmarks/README.md:12); one node here"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output",
                    default=os.path.join(REPO, "MICROBENCH.json"))
    ap.add_argument("--tasks", type=int, default=1_000_000)
    ap.add_argument("--only", nargs="+", default=None,
                    help="run only benches whose row name contains one of "
                         "these substrings; other stretch rows survive")
    args = ap.parse_args()

    benches = {
        "queued_tasks_1m": lambda: bench_1m_queued_tasks(args.tasks),
        "args_10k_single_task": bench_10k_args,
        "returns_1k_single_task": bench_1k_returns,
        "single_object_gib": bench_multi_gib_object,
        "batched_get_10k_objects": bench_10k_object_batched_get,
        "actors_1k_registered_responding": bench_1k_actors,
        "placement_groups_500": bench_500_pgs,
    }
    rows = []
    for name, fn in benches.items():
        if args.only is not None and not any(s in name
                                             for s in args.only):
            continue
        print(f"[envelope] {name}", flush=True)
        rows.append(fn())
        print(json.dumps(rows[-1]), flush=True)

    try:
        with open(args.output) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    env = doc.setdefault("envelope", {})
    # merge by row name: a partial refresh must not drop sibling rows
    merged = {r.get("name"): r for r in env.get("stretch", [])}
    for r in rows:
        merged[r.get("name")] = r
    env["stretch"] = list(merged.values())
    env["source"] = ("tests/test_scale_envelope.py (CI counts) + "
                     "benchmarks/scale_envelope.py (stretch)")
    with open(args.output, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"[envelope] wrote {args.output}")


if __name__ == "__main__":
    main()
