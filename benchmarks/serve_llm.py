"""LLM serving benchmark: continuous batching on the real chip.

The north-star serving row (BASELINE.md: "Serve llama-3-8b, TPU
replicas ... qps, p50/p99").  The reference's serve numbers are no-op
handlers (doc/source/serve/performance.md); this drives REAL token
generation through one TPU-resident engine replica and reports
tokens/s/chip, request qps, latency percentiles, and batch occupancy —
the numbers a model-serving user actually plans capacity with.

Run directly (defaults to gpt-small shapes, random weights):
  python benchmarks/serve_llm.py [--preset gpt-small] [--slots 8]
        [--requests 64] [--prompt-len 64] [--new-tokens 64] [--engine-only]

Prints one JSON line per scenario (collect_microbench.py ingests these).
"""

import argparse
import json
import os
import sys
import time

# repo-root import without PYTHONPATH (which would leak into the axon
# tunnel subprocess and break its own imports)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


try:
    from benchmarks._bench_util import percentiles as _percentiles
except ImportError:          # run as a script from benchmarks/
    from _bench_util import percentiles as _percentiles


def build_engine(preset: str, slots: int, seed: int = 0,
                 max_seq_len=None, block_size=16, paged=False,
                 page_size=64, kv_pool_pages=None):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.configs import get_config
    from ray_tpu.models.gpt import GPT
    from ray_tpu.serve.llm_engine import LLMEngine

    cfg = get_config(preset)
    model = GPT(cfg, decode=True)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 1), jnp.int32))["params"]
    return LLMEngine(cfg, params, num_slots=slots,
                     max_seq_len=max_seq_len,
                     block_size=block_size, paged=paged,
                     page_size=page_size,
                     kv_pool_pages=kv_pool_pages), cfg


def bench_engine(preset="gpt-small", slots=8, requests=64, prompt_len=64,
                 new_tokens=64, stagger_s=0.0, paged=False, page_size=64):
    """Drive the engine directly (no serve actor hop): the chip-side
    ceiling for one replica."""
    # KV allocation sized to the workload (prompt + generation + slack):
    # dense decode reads the whole cache row every step.  Paged mode
    # pools pages instead; size the pool so every request can prefill
    # ahead (the TTFT path) with slack.
    pool = None
    if paged:
        per_req = -(-(prompt_len + new_tokens) // page_size)
        pool = 1 + (requests + slots) * per_req
    eng, cfg = build_engine(preset, slots,
                            max_seq_len=2 * (prompt_len + new_tokens),
                            paged=paged, page_size=page_size,
                            kv_pool_pages=pool)
    try:
        return _drive_engine(eng, cfg, preset, slots, requests, prompt_len,
                             new_tokens, stagger_s, paged)
    finally:
        # a mid-bench failure must not leak the loop thread + device
        # buffers into the next suite scenario
        eng.close()


def _drive_engine(eng, cfg, preset, slots, requests, prompt_len,
                  new_tokens, stagger_s, paged):
    import asyncio

    vocab = cfg.vocab_size

    # compile every jit path at the bench shapes before timing, incl.
    # the saturation-burst decomposition in paged mode
    eng.warmup(prompt_lens=[prompt_len],
               burst=requests if paged else 0)
    eng.submit([7] * prompt_len, max_new_tokens=4, temperature=0.8)

    # single-threaded async submission: all requests enqueue at t~0 from
    # one event loop (a thread per request on this 1-core box measures
    # Python thread scheduling, not the engine)
    async def drive():
        futs = []
        for i in range(requests):
            prompt = [(i * 37 + j) % (vocab - 1) + 1
                      for j in range(prompt_len)]
            futs.append(eng.submit(prompt, max_new_tokens=new_tokens,
                                   temperature=0.8))
            if stagger_s:
                await asyncio.sleep(stagger_s)
        return await asyncio.gather(*futs)

    t0 = time.monotonic()
    results = asyncio.run(drive())
    wall = time.monotonic() - t0
    lats = [r.latency_s for r in results]
    ttfts = [r.time_to_first_token_s for r in results]

    tokens = sum(len(r.tokens) for r in results if r is not None)
    st = eng.stats.snapshot(eng.num_slots)
    p50, p99 = _percentiles(lats)
    t50, t99 = _percentiles(ttfts)
    return {
        "metric": "serve_llm_engine",
        "kv": "paged" if paged else "dense",
        "preset": preset,
        "num_slots": slots,
        "requests": requests,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "tokens_per_s": round(tokens / wall, 1),
        "qps": round(requests / wall, 2),
        "p50_ms": round(p50, 1),
        "p99_ms": round(p99, 1),
        "ttft_p50_ms": round(t50, 1),
        "ttft_p99_ms": round(t99, 1),
        "batch_occupancy": st["batch_occupancy"],
        "wall_s": round(wall, 2),
    }


def bench_serve(preset="gpt-small", slots=8, requests=64, prompt_len=64,
                new_tokens=64, paged=False, page_size=64):
    """Same load through a Serve replica handle: measures what a client
    of the deployment sees (adds router + actor-call overhead).

    Latency accounting matches bench_engine: every request is submitted
    up front and measured from its own submission instant (the round-4
    engine/handle rows used a concurrency window that hid queue wait —
    VERDICT round 4, "what's weak" #3)."""
    import ray_tpu
    from ray_tpu import serve

    pool = None
    if paged:
        per_req = -(-(prompt_len + new_tokens) // page_size)
        pool = 1 + (requests + slots) * per_req
    # replica __init__ compiles every engine specialization (warmup):
    # give actor creation room beyond the 60 s default.  num_tpus=1 on
    # both the cluster and the deployment: a replica without a TPU
    # lease is pinned to the CPU backend (see build_app docstring).
    # Setup sits INSIDE the try: a failed serve.run must still tear the
    # cluster down, or its daemons poison the rest of the --suite run.
    ray_tpu.init(num_cpus=4, num_tpus=1,
                 system_config={"actor_creation_timeout_s": 900.0})
    try:
        serve.start()
        app = serve.llm.build_app(preset=preset, num_slots=slots,
                                  max_concurrent_queries=2 * requests,
                                  max_seq_len=2 * (prompt_len + new_tokens),
                                  num_tpus=1, paged=paged,
                                  page_size=page_size, kv_pool_pages=pool,
                                  warmup_prompt_lens=[prompt_len],
                                  warmup_burst=requests if paged else 0)
        handle = serve.run(app, name="llm-bench")
        # warm the replica's jit paths
        ray_tpu.get(handle.remote({"prompt": [7] * prompt_len,
                                   "max_new_tokens": 4}), timeout=600)
        t0 = time.monotonic()
        pending = {}
        for i in range(requests):
            prompt = [(i * 37 + j) % 1000 + 1 for j in range(prompt_len)]
            ref = handle.remote({"prompt": prompt,
                                 "max_new_tokens": new_tokens,
                                 "temperature": 0.8})
            pending[ref] = time.monotonic()
        lats = []
        ttfts = []
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=600)
            for r in ready:
                out = ray_tpu.get(r)
                assert len(out["tokens"]) == new_tokens
                lats.append(time.monotonic() - pending.pop(r))
                ttfts.append(out["time_to_first_token_s"])
        wall = time.monotonic() - t0
        p50, p99 = _percentiles(lats)
        t50, t99 = _percentiles(ttfts)
        return {
            "metric": "serve_llm_handle",
            "kv": "paged" if paged else "dense",
            "preset": preset,
            "num_slots": slots,
            "requests": requests,
            "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "tokens_per_s": round(requests * new_tokens / wall, 1),
            "qps": round(requests / wall, 2),
            "p50_ms": round(p50, 1),
            "p99_ms": round(p99, 1),
            "ttft_p50_ms": round(t50, 1),
            "ttft_p99_ms": round(t99, 1),
            "wall_s": round(wall, 2),
        }
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass          # serve may not have started; cluster must die
        ray_tpu.shutdown()


def run_suite(slots: int, requests: int):
    """The MICROBENCH serve_llm matrix (one JSON line each):
      - gpt-small dense engine     (continuity with rounds 3-4)
      - gpt-small paged engine     (paged KV + prefill-ahead TTFT)
      - gpt-small paged handle     (client view through Serve)
      - gpt-large dense engine     (1B: the dense baseline)
      - gpt-large paged engine     (1B: the north-star scale row)
    """
    scenarios = [
        ("gpt-small", False, True),
        ("gpt-small", True, True),
        ("gpt-small", True, False),          # handle row
        ("gpt-large", False, True),
        ("gpt-large", True, True),
    ]
    failed = 0
    for preset, paged, engine_only in scenarios:
        try:
            if engine_only:
                row = bench_engine(preset, slots, requests,
                                   paged=paged)
            else:
                row = bench_serve(preset, slots, requests, paged=paged)
            print(json.dumps(row))
            sys.stdout.flush()
        except Exception as e:          # one scenario must not kill the rest
            failed += 1
            print(f"[serve_llm] {preset} paged={paged} "
                  f"engine_only={engine_only} FAILED: {e}",
                  file=sys.stderr, flush=True)
    if failed:
        # non-zero exit so the collector keeps the previous COMPLETE row
        # set instead of replacing it with this truncated one
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="gpt-small")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--engine-only", action="store_true")
    ap.add_argument("--paged", action="store_true")
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--suite", action="store_true",
                    help="emit the full MICROBENCH scenario matrix")
    args = ap.parse_args()

    if args.suite:
        run_suite(args.slots, args.requests)
        return
    row = bench_engine(args.preset, args.slots, args.requests,
                       args.prompt_len, args.new_tokens,
                       paged=args.paged, page_size=args.page_size)
    print(json.dumps(row))
    sys.stdout.flush()
    if not args.engine_only:
        row = bench_serve(args.preset, args.slots, args.requests,
                          args.prompt_len, args.new_tokens,
                          paged=args.paged, page_size=args.page_size)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
