"""Multi-chip sharded-training headline legs (docs/train_sharded.md).

Runs in its OWN process: the simulated multi-device mesh needs
``JAX_PLATFORMS=cpu`` + ``XLA_FLAGS=--xla_force_host_platform_device_
count=N`` pinned before the first backend touch, which bench.py (whose
backend is already live) cannot do — so bench.py launches this module as
a subprocess and folds its one JSON line into the headline output.

Two legs:

* ``multichip`` — a :class:`~ray_tpu.train.sharded.ShardedTrainer` gang
  (2 workers x N simulated devices each; the planner's fsdp x tp layout
  compiled into the step, int8 backward-overlapped host ring across
  workers) surviving one injected mid-run GRACEFUL slice preemption
  (PR 15 drain/evacuation -> SIGKILL -> replacement capacity -> gang
  recovery from the newest sharded checkpoint).  The goodput/MFU ledger
  is the referee: productive step time comes from the gang's step-stats
  reports, ``goodput_overall`` charges the outage + re-executed work
  against the fit's full wall clock, and the KV breadcrumbs bound
  re-executed steps by ``checkpoint_interval``.

* ``pipeline`` — a pp=2 MPMD :class:`~ray_tpu.train.sharded.
  PipelineRunner` over compiled-DAG shm channels; the zero-submission
  contract (per-microbatch task-submission cost ~ 0) is asserted by the
  ``ray_tpu_actor_tasks_submitted_total`` telemetry counter and reported
  as ``submissions_per_microbatch``.

The model is the gpt-large *family* scaled to CPU-feasible proxy shapes
by default (``--scale full`` runs the real 1.07B config — only sensible
on a many-core host); the row records the overrides so the number is
never mistaken for a real gpt-large run.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
import traceback

PROXY_OVERRIDES = {
    # gpt-large scaled to a 1-core CI box: same family (SwiGLU, RoPE,
    # scan_layers), ~7M params so compile + 8 steps fit the bench budget
    "n_layers": 4, "d_model": 256, "n_heads": 8, "n_kv_heads": 8,
    "d_ff": 1024, "vocab_size": 8192, "max_seq_len": 512, "remat": False,
}


def _setup_env(n_devices: int) -> None:
    """Pin the simulated mesh BEFORE any backend init; raylet/worker
    subprocesses inherit, so every gang worker sees n_devices CPU
    devices (same flag merge as __graft_entry__.dryrun_multichip)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    # a 1-core CI box cold-imports jax in every stage/gang actor; the
    # default 60 s actor-readiness window is routinely exceeded there
    os.environ.setdefault("RAY_TPU_ACTOR_CREATION_TIMEOUT_S", "600")
    flags = os.environ.get("XLA_FLAGS", "")
    pat = r"--xla_force_host_platform_device_count=(\d+)"
    m = re.search(pat, flags)
    if m is None:
        flags = (flags
                 + f" --xla_force_host_platform_device_count={n_devices}"
                 ).strip()
    elif int(m.group(1)) < n_devices:
        flags = re.sub(
            pat, f"--xla_force_host_platform_device_count={n_devices}",
            flags)
    os.environ["XLA_FLAGS"] = flags


def _model_overrides(args) -> dict:
    if args.scale == "full":
        return {}
    ov = dict(PROXY_OVERRIDES)
    ov["max_seq_len"] = max(ov["max_seq_len"], args.seq + 1)
    return ov


def _flops_per_token(cfg, seq: int) -> float:
    # PaLM-style: 6N per token fwd+bwd + attention 12*L*d*S (the same
    # arithmetic bench.py and sharded_train_loop use)
    return (6 * cfg.num_params()
            + 12 * cfg.n_layers * cfg.d_model * seq)


# ---------------------------------------------------------------------------
# leg 1: elastic multi-worker gang with injected preemption
# ---------------------------------------------------------------------------

def run_elastic(args) -> dict:
    import threading

    import ray_tpu
    from ray_tpu.air.config import FailureConfig, RunConfig
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental.state import (list_step_stats,
                                            training_summary)
    from ray_tpu.models import get_config
    from ray_tpu.runtime.core_worker import get_global_worker
    from ray_tpu.train.sharded import (ShardedRunConfig, ShardedTrainer,
                                       ShardingConfig, layout)

    tag = "bench-elastic"
    name = "bench-sharded-elastic"
    overrides = _model_overrides(args)
    model_cfg = get_config(args.model, **overrides)
    sharding = ShardingConfig(fsdp=2, tp=args.devices // 2)
    lplan = layout.plan(sharding, n_devices=args.devices)

    cluster = Cluster(head_resources={"CPU": 0})
    try:
        victim = cluster.add_node(resources={"CPU": 2, "slice": 2})
        cluster.add_node(resources={"CPU": 2, "slice": 2})
        cluster.wait_for_nodes(3)
        ray_tpu.init(num_cpus=0, address=cluster.address)
        gcs = get_global_worker().gcs

        steps, interval, world = args.steps, 2, 2
        run = ShardedRunConfig(
            sharding=sharding, model=args.model,
            model_overrides=overrides, num_workers=world, steps=steps,
            batch_per_worker=args.batch, seq_len=args.seq,
            checkpoint_interval=interval, quantize="int8",
            async_grad_sync=True, step_sleep_s=0.6, kv_breadcrumbs=True,
            peak_flops=args.peak * args.devices)
        trainer = ShardedTrainer(
            run, run_config=RunConfig(
                name=name, failure_config=FailureConfig(max_failures=3)),
            resources_per_worker={"CPU": 1, "slice": 1}, tag=tag)

        state: dict = {}

        def _preempt():
            # breadcrumb-triggered: drain once any rank has executed
            # past the first checkpoint, so the kill reliably lands
            # mid-run with restorable state behind it
            deadline = time.monotonic() + 600
            while time.monotonic() < deadline:
                done = [int(k.split("/")[3])
                        for k in gcs.kv_keys(f"shardsteps/{tag}/")]
                if done and max(done) >= interval:
                    break
                time.sleep(0.25)
            gcs.call("drain_node", {"node_id": victim.node_id,
                                    "grace_s": 60.0,
                                    "reason": "bench spot preemption"})
            # SIGKILL at the NODE_DRAINED edge: the victim's primary
            # copies (checkpoint shards included) have been evacuated to
            # survivors — the role a real preemption's grace window
            # plays.  Shards put AFTER the sweep can still be lost; the
            # restore chain's fallback covers that at +1 interval.
            # NEVER kill before the edge: an ungraceful kill loses the
            # victim rank's shards for EVERY chain entry and the run is
            # unrestorable by construction — on a loaded box (this
            # benchmark shares one core with everything else) the drain
            # can take minutes, so wait it out rather than lose the leg.
            deadline = time.monotonic() + 300
            drained = False
            while time.monotonic() < deadline:
                evs = gcs.call("list_cluster_events",
                               {"type": "NODE_DRAINED"}) or []
                if any(e.get("node_id") == victim.node_id for e in evs):
                    drained = True
                    break
                time.sleep(0.5)
            if not drained:
                state["drain_timeout"] = True
                return
            cluster.remove_node(victim)
            cluster.add_node(resources={"CPU": 2, "slice": 2})
            state["killed"] = True

        killer = threading.Thread(target=_preempt, daemon=True)
        t0 = time.monotonic()
        killer.start()
        result = trainer.fit()
        wall_s = time.monotonic() - t0
        killer.join(timeout=30)

        survived = (result.error is None
                    and result.metrics.get("step") == steps - 1)

        # re-executed (lost) work from the breadcrumbs, per rank
        re_executed = 0
        for rank in range(world):
            counts: dict = {}
            for key in gcs.kv_keys(f"shardsteps/{tag}/{rank}/"):
                step = int(key.split("/")[3])
                counts[step] = counts.get(step, 0) + 1
            re_executed = max(re_executed,
                              sum(c - 1 for c in counts.values()))

        # the ledger referee: each gang incarnation is its own run in
        # the GCS step table (fresh trial id per restart, group =
        # host-collective name), and this cluster ran nothing else — so
        # fold the whole run directory.  ``agg`` keeps the newest
        # incarnation ledger; ``productive_ms`` counts each (rank, step)
        # once (newest execution wins) so the overall goodput charges
        # the outage, compile/restore time AND re-executed work against
        # the fit's full wall clock.
        directory = list_step_stats(steps_limit=1) or {}
        agg: dict = {}
        uniq: dict = {}
        for row in directory.get("runs", []):
            rid = row["run"]
            t = list_step_stats(run=rid, steps_limit=4 * steps) or {}
            for srow in t.get("steps", []):
                for rank, rec in (srow.get("ranks") or {}).items():
                    uniq[(rank, srow["step"])] = rec.get("step_ms", 0.0)
            s = training_summary(run=rid) or {}
            if s.get("aggregate"):
                agg = s["aggregate"]
        productive_ms = sum(uniq.values())
        goodput_overall = round(
            productive_ms / (wall_s * 1000.0 * world), 4) \
            if wall_s > 0 else 0.0
        tokens_total = world * steps * args.batch * args.seq
        # 6 decimals: against a TPU peak the simulated-CPU MFU is
        # ~1e-6 — visible precision keeps the column a consistency
        # check instead of a constant 0.0
        mfu_overall = 0.0
        if productive_ms > 0 and args.peak > 0:
            mfu_overall = round(
                _flops_per_token(model_cfg, args.seq) * tokens_total
                / (productive_ms / 1000.0)
                / (args.peak * args.devices), 6)

        return {
            "config": args.model,
            "model_overrides": overrides or "none",
            "params": model_cfg.num_params(),
            "world": world,
            "devices_per_worker": args.devices,
            "mesh_per_worker": {k: v for k, v in lplan.mesh_shape.items()
                                if v > 1},
            "grad_sync": "int8 async host ring (dp across workers)",
            "steps": steps,
            "checkpoint_interval": interval,
            "preempted": (
                "survived" if survived and state.get("killed")
                else "NOT-INJECTED (drain lagged the run; undisturbed)"
                if survived else "FAILED"),
            "re_executed_steps": re_executed,
            "final_loss": result.metrics.get("loss")
            if result.error is None else None,
            "wall_s": round(wall_s, 1),
            "goodput": agg.get("goodput"),
            "ledger_mfu": agg.get("mfu"),
            "goodput_overall": goodput_overall,
            "mfu_overall": mfu_overall,
            "tokens_per_s": agg.get("tokens_per_s"),
            "error": str(result.error) if result.error else None,
        }
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# leg 2: pp=2 MPMD pipeline over compiled-DAG channels
# ---------------------------------------------------------------------------

def run_pipeline(args) -> dict:
    import ray_tpu
    from ray_tpu.models import get_config
    from ray_tpu.train.sharded import PipelineRunner, PipelineSpec

    overrides = _model_overrides(args)
    model_cfg = get_config(args.model, **overrides)
    spec = PipelineSpec(
        model=args.model, model_overrides=overrides, pp=2,
        microbatches=4, microbatch_size=2, seq_len=args.seq,
        steps=args.pp_steps, lr=1e-2, seed=0, threaded_ops=True)

    ray_tpu.init(num_cpus=4)
    runner = None
    try:
        t0 = time.monotonic()
        runner = PipelineRunner(spec)
        compile_s = time.monotonic() - t0
        t1 = time.monotonic()
        summary = runner.train(spec.steps)
        wall_s = time.monotonic() - t1
        tokens = (spec.steps * spec.microbatches * spec.microbatch_size
                  * spec.seq_len)
        return {
            "config": args.model,
            "model_overrides": overrides or "none",
            "params": model_cfg.num_params(),
            "pp": spec.pp,
            "schedule": "1F1B over shm channels (threaded_ops)",
            "microbatches": spec.microbatches,
            "steps": summary["steps"],
            "final_loss": round(summary["final_loss"], 4),
            "dag_executes": summary["executes"],
            # the zero-submission contract: the hot loop moved the
            # classic actor-task counter by exactly nothing
            "classic_submits_hot_loop": summary["classic_submits_hot_loop"],
            "submissions_per_microbatch":
                summary["submissions_per_microbatch"],
            "tokens_per_s": round(tokens / wall_s, 1) if wall_s > 0
            else 0.0,
            "setup_s": round(compile_s, 1),
            "wall_s": round(wall_s, 1),
        }
    finally:
        if runner is not None:
            runner.shutdown()
        ray_tpu.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--devices", type=int,
                    default=int(os.environ.get(
                        "RAY_TPU_BENCH_SHARDED_DEVICES", "4")),
                    help="simulated devices per gang worker (>= 4 for "
                         "the fsdp x tp acceptance layout)")
    ap.add_argument("--model", default="gpt-large")
    ap.add_argument("--scale",
                    default=os.environ.get("RAY_TPU_BENCH_SHARDED_SCALE",
                                           "proxy"),
                    choices=("proxy", "full"))
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--pp-steps", type=int, default=3, dest="pp_steps")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--peak", type=float, default=197e12,
                    help="per-device peak FLOPs for the MFU columns "
                         "(bench.py's v5e default: simulated-CPU MFU is "
                         "a consistency check, not a hardware claim)")
    ap.add_argument("--legs", default="both",
                    choices=("both", "elastic", "pipeline"))
    args = ap.parse_args(argv)

    _setup_env(args.devices)
    out = {"device_sim": f"cpu x{args.devices}", "scale": args.scale}
    if args.legs in ("both", "elastic"):
        try:
            out["multichip"] = run_elastic(args)
        except Exception as e:  # degrade to a named error row
            traceback.print_exc()
            out["multichip"] = {"error": f"{type(e).__name__}: {e}"}
    if args.legs in ("both", "pipeline"):
        try:
            out["pipeline"] = run_pipeline(args)
        except Exception as e:
            traceback.print_exc()
            out["pipeline"] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out), flush=True)
    ok = all(isinstance(v, dict) and not v.get("error")
             for k, v in out.items() if k in ("multichip", "pipeline"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
