"""Serve LLM token streaming: print tokens as the replica decodes them.

Drives a Serve LLM deployment (tiny preset, runnable under
JAX_PLATFORMS=cpu) through the streaming path: the handle's
``remote_streaming`` call routes to the replica's streaming entrypoint
with ``num_returns="streaming"``, so every generated token arrives as
its own ObjectRef the decode step it is produced — the first token
prints while the request is still generating, instead of after the
whole completion (docs/streaming_generators.md).

Run:  JAX_PLATFORMS=cpu python examples/streaming_tokens.py
"""
import time

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)
    try:
        app = serve.llm.build_app(
            preset="tiny", num_slots=2, block_size=4, max_seq_len=128,
            warmup_prompt_lens=[16])
        handle = serve.run(app, name="llm-stream")

        request = {"prompt": [1, 2, 3, 4, 5], "max_new_tokens": 24}
        t0 = time.monotonic()
        # route to LLMServer.stream: an async generator yielding one
        # {"token": id} dict per decoded token, then a summary dict
        gen = handle.stream.remote_streaming(request)
        first_at = None
        print("tokens: ", end="", flush=True)
        for ref in gen:
            item = ray_tpu.get(ref)
            if "token" in item:
                if first_at is None:
                    first_at = time.monotonic() - t0
                print(item["token"], end=" ", flush=True)
            else:
                total = time.monotonic() - t0
                print(f"\nfinish_reason={item['finish_reason']} "
                      f"num_tokens={item['num_tokens']}")
                print(f"first token after {first_at:.3f}s, "
                      f"full stream after {total:.3f}s")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
