"""RL: PPO with rollout actors + the mesh-jitted learner (cf. reference
rllib quickstart)."""
import ray_tpu
from ray_tpu.rl import PPOConfig


def main():
    ray_tpu.init(num_cpus=4)
    try:
        algo = (PPOConfig()
                .environment("CartPole-v1")
                .rollouts(num_rollout_workers=2, num_envs_per_worker=2,
                          rollout_fragment_length=100)
                .training(train_batch_size=400, sgd_minibatch_size=128,
                          num_sgd_iter=6, entropy_coeff=0.01)
                .debugging(seed=0)
                .build())
        for i in range(5):
            result = algo.train()
            print(f"iter {i}: reward_mean="
                  f"{result['episode_reward_mean']:.1f} "
                  f"steps={result['timesteps_total']}")
        algo.stop()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
