"""Sharded LM training: one jitted step carries DP x FSDP x TP; XLA
emits the collectives (the TPU-native replacement for DDP wiring)."""
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import GPT, get_config
from ray_tpu.parallel import MeshConfig, build_mesh
from ray_tpu.train.step import OptimizerConfig, make_sharded_train


def main():
    n = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1, fsdp=2 if n % 2 == 0 else 1))
    print("mesh:", dict(mesh.shape))
    cfg = get_config("tiny", max_seq_len=128)
    model = GPT(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"tokens": jnp.asarray(
        rng.integers(0, cfg.vocab_size, (8, 129)), jnp.int32)}
    init_fn, step_fn, _, _ = make_sharded_train(
        model, mesh, OptimizerConfig(warmup_steps=5, decay_steps=100),
        example_batch=batch)
    state = init_fn(jax.random.PRNGKey(0), batch)   # born sharded
    for i in range(10):
        state, metrics = step_fn(state, batch)
        if i % 3 == 0:
            print(f"step {i}: loss={float(metrics['loss']):.4f}")


if __name__ == "__main__":
    main()
