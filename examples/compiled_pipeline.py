"""Pipeline-parallel inference with a compiled DAG (docs/compiled_dag.md).

MPMD pipeline parallelism (PAPERS.md arXiv:2412.14374) is exactly the
dataflow shape compiled DAGs are built for: each pipeline stage is an
actor holding its own layer parameters; the driver streams microbatches
through ``CompiledDAG.execute`` with ``max_inflight`` > 1 so stage K is
computing microbatch N while stage K+1 computes microbatch N-1 — and,
because the graph is compiled, steady-state execution costs zero task
submissions: every hop is a shared-memory channel write.

Run:  JAX_PLATFORMS=cpu python examples/compiled_pipeline.py
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.dag import InputNode  # noqa: E402

N_STAGES = 3
HIDDEN = 256
MICROBATCHES = 32
MAX_INFLIGHT = 4


@ray_tpu.remote
class PipelineStage:
    """One stage of the model: y = relu(x @ W) with stage-local W."""

    def __init__(self, seed: int):
        rng = np.random.default_rng(seed)
        self.w = rng.standard_normal((HIDDEN, HIDDEN)).astype(np.float32)
        self.w /= np.sqrt(HIDDEN)

    def forward(self, x):
        # a stage holds ~25 ms of compute so the pipeline overlap is
        # visible even on this 1-2 core box
        y = np.maximum(x @ self.w, 0.0)
        for _ in range(4):
            y = np.maximum(y @ self.w * 0.5 + y * 0.5, 0.0)
        return y


def main():
    ray_tpu.init(num_cpus=N_STAGES + 1,
                 object_store_memory=256 * 1024 * 1024)
    try:
        with InputNode() as microbatch:
            node = microbatch
            for i in range(N_STAGES):
                node = PipelineStage.bind(i).forward.bind(node)

        pipeline = node.experimental_compile(
            max_inflight=MAX_INFLIGHT,
            buffer_size_bytes=4 * HIDDEN * HIDDEN,
            name="mpmd-pipeline")
        x = np.ones((16, HIDDEN), np.float32)
        out = pipeline.execute(x).get(timeout=120)      # warm every stage
        print(f"pipeline up: {N_STAGES} stages, output {out.shape}")

        # sequential reference: one microbatch at a time (no overlap)
        t0 = time.perf_counter()
        for _ in range(MICROBATCHES):
            pipeline.execute(x).get(timeout=120)
        seq_s = time.perf_counter() - t0

        # pipelined: keep max_inflight microbatches in flight
        t0 = time.perf_counter()
        refs = []
        for _ in range(MICROBATCHES):
            refs.append(pipeline.execute(x))
        for ref in refs:
            ref.get(timeout=120)
        pipe_s = time.perf_counter() - t0

        print(f"sequential: {seq_s:.2f}s  "
              f"({seq_s / MICROBATCHES * 1e3:.1f} ms/microbatch)")
        print(f"pipelined (inflight={MAX_INFLIGHT}): {pipe_s:.2f}s  "
              f"({pipe_s / MICROBATCHES * 1e3:.1f} ms/microbatch)  "
              f"-> {seq_s / pipe_s:.2f}x")
        pipeline.teardown()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
