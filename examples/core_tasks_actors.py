"""Core API tour: tasks, objects, actors (cf. reference docs quickstart)."""
import ray_tpu


@ray_tpu.remote
def square(x):
    return x * x


@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k=1):
        self.n += k
        return self.n


def main():
    ray_tpu.init(num_cpus=2)
    try:
        print("tasks:", ray_tpu.get([square.remote(i) for i in range(5)]))
        big = ray_tpu.put(list(range(10_000)))      # object store
        print("object len:", len(ray_tpu.get(big)))
        c = Counter.remote()
        for _ in range(3):
            c.add.remote()
        print("counter:", ray_tpu.get(c.add.remote(0)))
        ready, rest = ray_tpu.wait(
            [square.remote(i) for i in range(4)], num_returns=2)
        print("wait:", len(ready), "ready,", len(rest), "pending")
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
