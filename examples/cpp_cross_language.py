"""C++ tasks and actors from a Python driver (cross-language calls).

The functions/classes live in the native worker binary
(csrc/cpp_builtin_functions.cc, registered with RAY_TPU_CPP_FUNCTION /
RAY_TPU_CPP_ACTOR); build with `make -C csrc`.  Point the
`cpp_worker_binary` config flag at your own build to expose your own.
A C++ program can also join the cluster directly — see
csrc/cpp_driver_demo.cc for the native-driver (cpp_api.h) version of
this script.
"""

import ray_tpu

# two dedicated actor workers + task workers need more CPU slots than a
# tiny CI box advertises; resources are logical
ray_tpu.init(num_cpus=4)

# --- cpp tasks: leases route to native workers (language=cpp pools) ----
add = ray_tpu.cpp_function("Add")
print("Add(1, 2, 3)        =", ray_tpu.get(add.remote(1, 2, 3)))
print("Fib(80)             =",
      ray_tpu.get(ray_tpu.cpp_function("Fib").remote(80)))
lo, hi = ray_tpu.get(list(
    ray_tpu.cpp_function("MinMax", num_returns=2).remote(7, 3, 9, 1)))
print("MinMax(7,3,9,1)     =", (lo, hi))

# --- cpp actors: native state, ordered method pipeline -----------------
counter = ray_tpu.cpp_actor_class("Counter").remote(100)
for _ in range(3):
    print("counter.inc()       =", ray_tpu.get(counter.inc.remote()))

kv = ray_tpu.cpp_actor_class("Kv").remote()
ray_tpu.get(kv.put.remote("config", {"lr": 0.001, "layers": [256, 256]}))
print("kv.get('config')    =", ray_tpu.get(kv.get.remote("config")))

# --- errors cross the language boundary as TaskError -------------------
try:
    ray_tpu.get(ray_tpu.cpp_function("Fail").remote("native explosion"))
except ray_tpu.exceptions.TaskError as e:
    print("cpp error surfaced  =", str(e).splitlines()[0])

ray_tpu.kill(counter)
ray_tpu.shutdown()
