"""Hyperparameter search: Tuner + ASHA early stopping (cf. reference
tune quickstart)."""
import ray_tpu
from ray_tpu.air import session
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner, loguniform


def trainable(config):
    # a fake objective that rewards lr near 1e-2
    import math
    for i in range(10):
        score = -abs(math.log10(config["lr"]) + 2) * (1 - i / 20)
        session.report({"score": score})


def main():
    ray_tpu.init(num_cpus=4)
    try:
        grid = Tuner(
            trainable,
            param_space={"lr": loguniform(1e-5, 1e-1)},
            tune_config=TuneConfig(
                metric="score", mode="max", num_samples=8,
                max_concurrent_trials=4,
                scheduler=ASHAScheduler(metric="score", mode="max",
                                        grace_period=2,
                                        reduction_factor=2, max_t=10)),
        ).fit()
        best = grid.get_best_result()
        print("best lr:", best.metrics["config"]["lr"],
              "score:", best.metrics["score"])
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
