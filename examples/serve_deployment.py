"""Model serving: deployment + handle + HTTP (cf. reference serve
quickstart)."""
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(num_replicas=2)
class Doubler:
    def __call__(self, request):
        return {"result": 2 * int(request["x"])}


def main():
    ray_tpu.init(num_cpus=4)
    try:
        handle = serve.run(Doubler.bind(), name="doubler")
        out = ray_tpu.get(handle.remote({"x": 21}))
        print("handle call:", out)
        status = serve.status()
        print("serve status:", {k: v["status"]
                                for k, v in status.items()})
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
