"""Dataset pipeline: read -> transform -> shuffle -> split -> iterate
(cf. reference data quickstart)."""
import numpy as np

import ray_tpu
import ray_tpu.data as rdata


def main():
    ray_tpu.init(num_cpus=2)
    try:
        ds = rdata.range(1000, parallelism=4)      # rows: {"id": i}
        ds = ds.map(lambda r: {"x": r["id"], "y": r["id"] * 2})
        ds = ds.filter(lambda r: r["x"] % 3 == 0)
        ds = ds.random_shuffle(seed=0)
        train, test = ds.train_test_split(test_size=0.25)
        print("train rows:", train.count(), "test rows:", test.count())
        batch = next(train.iter_batches(batch_size=32,
                                        batch_format="pandas"))
        print("first batch mean y:", float(np.mean(batch["y"])))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
